"""MQTT driver (gated: requires ``paho-mqtt``).

Reference: pkg/gofr/datasource/pubsub/mqtt/mqtt.go —
  - per-topic buffered channel (size 10) fed by the subscription callback
    (mqtt.go:145-184)
  - QoS/retained config, default public broker fallback (:55-78)
  - extended ops: SubscribeWithFunction, Unsubscribe, Disconnect, Ping
    (:253-306)
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Optional

from .. import Health, STATUS_DOWN, STATUS_UP
from . import Message


class MQTTClient:
    """Seam: ``client_factory(client_id)`` returns a paho-shaped client
    (connect, loop_start/stop, subscribe/unsubscribe, publish,
    message_callback_add, is_connected, disconnect, settable
    ``on_message``) — the reference's mqtt/interface.go mock seam. Default
    builds the real paho client (gated import)."""

    def __init__(self, broker: str = "broker.hivemq.com", port: int = 1883,
                 client_id: str = "gofr-mqtt", qos: int = 0,
                 retained: bool = False, logger=None, client_factory=None):
        if client_factory is None:
            try:
                import paho.mqtt.client as mqtt  # gated import
            except ImportError as e:
                raise RuntimeError(
                    "MQTT backend requires the paho-mqtt package") from e

            def client_factory(cid):
                return mqtt.Client(client_id=cid)
        self.broker = broker
        self.port = port
        self.qos = qos
        self.retained = retained
        self.logger = logger
        # reference mqtt.go:150-157: per-topic buffered channel, size 10
        self._queues: dict[str, queue.Queue] = {}
        self._lock = threading.Lock()
        self._client = client_factory(client_id)
        self._client.on_message = self._on_message
        self._client.connect(broker, port)
        self._client.loop_start()

    def _queue(self, topic: str) -> queue.Queue:
        with self._lock:
            if topic not in self._queues:
                self._queues[topic] = queue.Queue(maxsize=10)
            return self._queues[topic]

    def _on_message(self, client, userdata, msg) -> None:
        q = self._queue(msg.topic)
        try:
            q.put_nowait(msg)
        except queue.Full:
            if self.logger is not None:
                self.logger.warn({"event": "mqtt queue full, dropping",
                                  "topic": msg.topic})

    def publish(self, topic: str, message: bytes) -> None:
        info = self._client.publish(topic, message, qos=self.qos,
                                    retain=self.retained)
        info.wait_for_publish(timeout=30)

    def subscribe(self, topic: str, timeout: Optional[float] = None) -> Message | None:
        self._queue(topic)  # ensure the buffer exists before subscribing
        self._client.subscribe(topic, qos=self.qos)
        try:
            msg = self._queue(topic).get(
                timeout=timeout if timeout is not None else 30.0)
        except queue.Empty:
            return None
        # MQTT QoS handles delivery; commit is a no-op (reference mqtt
        # message.go Commit is empty)
        return Message(topic, msg.payload, metadata={"qos": str(msg.qos)})

    def subscribe_with_function(self, topic: str,
                                fn: Callable[[Message], None]) -> None:
        """reference mqtt.go:253 SubscribeWithFunction."""
        def on_msg(client, userdata, msg):
            fn(Message(msg.topic, msg.payload, metadata={"qos": str(msg.qos)}))

        self._client.message_callback_add(topic, on_msg)
        self._client.subscribe(topic, qos=self.qos)

    def unsubscribe(self, topic: str) -> None:
        self._client.unsubscribe(topic)
        with self._lock:
            self._queues.pop(topic, None)

    def create_topic(self, name: str) -> None:
        pass  # MQTT topics are implicit

    def delete_topic(self, name: str) -> None:
        self.unsubscribe(name)

    def ping(self) -> bool:
        return self._client.is_connected()

    def health_check(self) -> Health:
        up = False
        try:
            up = self._client.is_connected()
        except Exception:
            pass
        return Health(status=STATUS_UP if up else STATUS_DOWN,
                      details={"backend": "MQTT",
                               "broker": f"{self.broker}:{self.port}"})

    def close(self) -> None:
        try:
            self._client.loop_stop()
            self._client.disconnect()
        except Exception:
            pass
