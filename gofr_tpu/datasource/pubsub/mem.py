"""In-process broker with Kafka-like consumer-group offset semantics.

This is both the hermetic test seam (the reference mocks its broker behind
Reader/Writer interfaces, kafka/interfaces.go:9-25 + mock_interfaces.go) and
a real local-dev backend: messages are durable for the process lifetime,
consumer groups track a committed offset, and an uncommitted message is
redelivered when a fresh client (same group) attaches — at-least-once, like
Kafka consumer groups with commit-on-success (reference kafka/message.go:25).
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from .. import Health, STATUS_UP
from . import Message

# process-global topic log so independent MemBroker instances (publisher app
# + subscriber app in one test) see the same broker, like a real out-of-
# process broker would behave
_GLOBAL_LOCK = threading.Lock()
_TOPICS: dict[str, list[bytes]] = {}
_COMMITTED: dict[tuple[str, str], int] = {}  # (group, topic) -> offset
_CONDS: dict[str, threading.Condition] = {}


def reset() -> None:
    """Test hook: wipe all topics and offsets."""
    with _GLOBAL_LOCK:
        _TOPICS.clear()
        _COMMITTED.clear()
        _CONDS.clear()


def _cond(topic: str) -> threading.Condition:
    with _GLOBAL_LOCK:
        if topic not in _CONDS:
            _CONDS[topic] = threading.Condition()
        return _CONDS[topic]


class MemBroker:
    def __init__(self, consumer_group: str = "gofr"):
        self.consumer_group = consumer_group
        # delivered-but-not-committed cursor, per topic, local to this client
        # (a restart constructs a new client, which resumes from committed —
        # that is what produces at-least-once redelivery)
        self._delivered: dict[str, int] = {}

    # -- admin (reference kafka.go:180-196 Create/DeleteTopic) ---------------
    def create_topic(self, name: str) -> None:
        with _GLOBAL_LOCK:
            _TOPICS.setdefault(name, [])

    def delete_topic(self, name: str) -> None:
        with _GLOBAL_LOCK:
            _TOPICS.pop(name, None)
            for key in [k for k in _COMMITTED if k[1] == name]:
                del _COMMITTED[key]

    def topics(self) -> list[str]:
        with _GLOBAL_LOCK:
            return list(_TOPICS)

    # -- produce/consume ----------------------------------------------------
    def publish(self, topic: str, message: bytes) -> None:
        cond = _cond(topic)
        with cond:
            with _GLOBAL_LOCK:
                _TOPICS.setdefault(topic, []).append(message)
            cond.notify_all()

    def subscribe(self, topic: str, timeout: Optional[float] = None) -> Message | None:
        """Next message for this consumer group; blocks up to ``timeout``."""
        cond = _cond(topic)
        deadline = None if timeout is None else time.monotonic() + timeout
        with cond:
            while True:
                with _GLOBAL_LOCK:
                    log = _TOPICS.setdefault(topic, [])
                    committed = _COMMITTED.get((self.consumer_group, topic), 0)
                    cursor = max(self._delivered.get(topic, 0), committed)
                    if cursor < len(log):
                        value = log[cursor]
                        self._delivered[topic] = cursor + 1
                        offset = cursor
                        return Message(
                            topic, value,
                            metadata={"offset": str(offset),
                                      "group": self.consumer_group},
                            committer=lambda o=offset: self._commit(topic, o))
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return None
                    cond.wait(remaining)
                else:
                    cond.wait()

    def _commit(self, topic: str, offset: int) -> None:
        with _GLOBAL_LOCK:
            key = (self.consumer_group, topic)
            _COMMITTED[key] = max(_COMMITTED.get(key, 0), offset + 1)

    # -- health -------------------------------------------------------------
    def health_check(self) -> Health:
        with _GLOBAL_LOCK:
            return Health(status=STATUS_UP, details={
                "backend": "MEM",
                "topics": {t: len(v) for t, v in _TOPICS.items()},
                "group": self.consumer_group})

    def close(self) -> None:
        pass
