"""Kafka driver (gated: requires the optional ``kafka-python`` client).

Reference: pkg/gofr/datasource/pubsub/kafka/kafka.go —
  - lazy per-topic readers in a consumer group, guarded by a lock
    (kafka.go:117-153, getNewReader :166, RWMutex :33)
  - single shared producer (:41-76), publish :90-115
  - commit-on-success via the message committer (message.go:25)
  - create/delete topic via the admin client (:180-196)
  - health = broker reachability + reader/writer stats (health.go:9-53)

Seam: the driver talks to Kafka only through a ``KafkaFactory``
(producer/consumer/commit/admin) — the reference's
``Reader/Writer/Connection`` interfaces (kafka/interfaces.go:9-25) with
checked-in mocks — so driver logic (lazy readers, offset-precise commit,
health shape) is testable against a fake with no broker
(tests/test_pubsub_drivers.py).
"""

from __future__ import annotations

import threading
from typing import Optional

from .. import Health, STATUS_DOWN, STATUS_UP
from . import Message


class KafkaFactory:
    """Default factory over kafka-python; replace with a fake in tests.

    The surface is exactly what the driver uses:
      producer() -> obj with send(topic, bytes).get(timeout),
                    bootstrap_connected(), close()
      consumer(topic, group, offset) -> obj with
                    poll(timeout_ms=, max_records=) -> {tp: [records]},
                    close(); records have topic/partition/offset/value
      commit(consumer, record) -> commit THAT record's offset
      create_topic(name) / delete_topic(name)
    """

    def __init__(self, brokers: list[str]):
        try:
            import kafka  # noqa: F401  (gated import)
        except ImportError as e:
            raise RuntimeError(
                "KAFKA backend requires the kafka-python package") from e
        self._kafka = kafka
        self.brokers = brokers

    def producer(self):
        return self._kafka.KafkaProducer(bootstrap_servers=self.brokers)

    def consumer(self, topic: str, group: str, offset: str):
        return self._kafka.KafkaConsumer(
            topic, bootstrap_servers=self.brokers, group_id=group,
            auto_offset_reset=offset, enable_auto_commit=False)

    def commit(self, consumer, rec) -> None:
        # commit THIS message's offset, not the consumer's current
        # position — committing the position would mark earlier
        # uncommitted (failed) messages as processed and break
        # at-least-once (reference kafka/message.go:25-30)
        from kafka import TopicPartition
        from kafka.structs import OffsetAndMetadata

        consumer.commit({TopicPartition(rec.topic, rec.partition):
                         OffsetAndMetadata(rec.offset + 1, None)})

    def create_topic(self, name: str) -> None:
        from kafka.admin import KafkaAdminClient, NewTopic

        admin = KafkaAdminClient(bootstrap_servers=self.brokers)
        try:
            admin.create_topics([NewTopic(name, num_partitions=1,
                                          replication_factor=1)])
        finally:
            admin.close()

    def delete_topic(self, name: str) -> None:
        from kafka.admin import KafkaAdminClient

        admin = KafkaAdminClient(bootstrap_servers=self.brokers)
        try:
            admin.delete_topics([name])
        finally:
            admin.close()


class KafkaClient:
    def __init__(self, brokers: str, consumer_group: str = "gofr",
                 partition_size: int = 0, offset: str = "latest", logger=None,
                 factory=None):
        self.brokers = brokers.split(",")
        self.consumer_group = consumer_group
        self.offset = ("earliest" if offset.lower() in ("earliest", "oldest")
                       else "latest")
        self.logger = logger
        self._factory = factory if factory is not None \
            else KafkaFactory(self.brokers)
        self._producer = self._factory.producer()
        self._consumers: dict[str, object] = {}
        self._lock = threading.Lock()

    def _consumer(self, topic: str):
        """Lazy per-topic consumer (reference kafka.go:166 getNewReader)."""
        with self._lock:
            if topic not in self._consumers:
                self._consumers[topic] = self._factory.consumer(
                    topic, self.consumer_group, self.offset)
            return self._consumers[topic]

    def publish(self, topic: str, message: bytes) -> None:
        self._producer.send(topic, message).get(timeout=30)

    def subscribe(self, topic: str, timeout: Optional[float] = None) -> Message | None:
        consumer = self._consumer(topic)
        ms = int((0.5 if timeout is None else timeout) * 1000)
        batch = consumer.poll(timeout_ms=ms, max_records=1)
        for records in batch.values():
            for rec in records:
                def commit(rec=rec):
                    self._factory.commit(consumer, rec)

                return Message(
                    topic, rec.value,
                    metadata={"offset": str(rec.offset),
                              "partition": str(rec.partition)},
                    committer=commit)
        return None

    def create_topic(self, name: str) -> None:
        self._factory.create_topic(name)

    def delete_topic(self, name: str) -> None:
        self._factory.delete_topic(name)

    def health_check(self) -> Health:
        try:
            ok = self._producer.bootstrap_connected()
            return Health(status=STATUS_UP if ok else STATUS_DOWN,
                          details={"backend": "KAFKA", "brokers": self.brokers,
                                   "readers": list(self._consumers)})
        except Exception as e:
            return Health(status=STATUS_DOWN,
                          details={"backend": "KAFKA", "error": repr(e)})

    def close(self) -> None:
        try:
            self._producer.close()
        except Exception:
            pass
        for c in self._consumers.values():
            try:
                c.close()
            except Exception:
                pass
