"""Kafka driver (gated: requires the optional ``kafka-python`` client).

Reference: pkg/gofr/datasource/pubsub/kafka/kafka.go —
  - lazy per-topic readers in a consumer group, guarded by a lock
    (kafka.go:117-153, getNewReader :166, RWMutex :33)
  - single shared producer (:41-76), publish :90-115
  - commit-on-success via the message committer (message.go:25)
  - create/delete topic via the admin client (:180-196)
  - health = broker reachability + reader/writer stats (health.go:9-53)
"""

from __future__ import annotations

import threading
from typing import Optional

from .. import Health, STATUS_DOWN, STATUS_UP
from . import Message


class KafkaClient:
    def __init__(self, brokers: str, consumer_group: str = "gofr",
                 partition_size: int = 0, offset: str = "latest", logger=None):
        try:
            import kafka  # noqa: F401  (gated import)
        except ImportError as e:
            raise RuntimeError(
                "KAFKA backend requires the kafka-python package") from e
        from kafka import KafkaProducer

        self._kafka = kafka
        self.brokers = brokers.split(",")
        self.consumer_group = consumer_group
        self.offset = "earliest" if offset.lower() in ("earliest", "oldest") else "latest"
        self.logger = logger
        self._producer = KafkaProducer(bootstrap_servers=self.brokers)
        self._consumers: dict[str, object] = {}
        self._lock = threading.Lock()

    def _consumer(self, topic: str):
        """Lazy per-topic consumer (reference kafka.go:166 getNewReader)."""
        with self._lock:
            if topic not in self._consumers:
                self._consumers[topic] = self._kafka.KafkaConsumer(
                    topic, bootstrap_servers=self.brokers,
                    group_id=self.consumer_group,
                    auto_offset_reset=self.offset,
                    enable_auto_commit=False)
            return self._consumers[topic]

    def publish(self, topic: str, message: bytes) -> None:
        self._producer.send(topic, message).get(timeout=30)

    def subscribe(self, topic: str, timeout: Optional[float] = None) -> Message | None:
        consumer = self._consumer(topic)
        ms = int((0.5 if timeout is None else timeout) * 1000)
        batch = consumer.poll(timeout_ms=ms, max_records=1)
        for records in batch.values():
            for rec in records:
                def commit(rec=rec):
                    # commit THIS message's offset, not the consumer's
                    # current position — committing the position would mark
                    # earlier uncommitted (failed) messages as processed and
                    # break at-least-once (reference kafka/message.go:25-30
                    # commits the specific message)
                    from kafka import TopicPartition
                    from kafka.structs import OffsetAndMetadata

                    consumer.commit({
                        TopicPartition(rec.topic, rec.partition):
                            OffsetAndMetadata(rec.offset + 1, None)})

                return Message(
                    topic, rec.value,
                    metadata={"offset": str(rec.offset),
                              "partition": str(rec.partition)},
                    committer=commit)
        return None

    def create_topic(self, name: str) -> None:
        from kafka.admin import KafkaAdminClient, NewTopic

        admin = KafkaAdminClient(bootstrap_servers=self.brokers)
        try:
            admin.create_topics([NewTopic(name, num_partitions=1,
                                          replication_factor=1)])
        finally:
            admin.close()

    def delete_topic(self, name: str) -> None:
        from kafka.admin import KafkaAdminClient

        admin = KafkaAdminClient(bootstrap_servers=self.brokers)
        try:
            admin.delete_topics([name])
        finally:
            admin.close()

    def health_check(self) -> Health:
        try:
            ok = self._producer.bootstrap_connected()
            return Health(status=STATUS_UP if ok else STATUS_DOWN,
                          details={"backend": "KAFKA", "brokers": self.brokers,
                                   "readers": list(self._consumers)})
        except Exception as e:
            return Health(status=STATUS_DOWN,
                          details={"backend": "KAFKA", "error": repr(e)})

    def close(self) -> None:
        try:
            self._producer.close()
        except Exception:
            pass
        for c in self._consumers.values():
            try:
                c.close()
            except Exception:
                pass
