"""Broker-agnostic pub/sub layer.

Reference: pkg/gofr/datasource/pubsub/ —
  - ``Client/Publisher/Subscriber/Committer`` interfaces (interface.go:9-28)
  - ``Message`` implements the framework Request surface
    (Context/Param/PathParam/Bind/HostName — message.go:8-50) so pub/sub
    handlers reuse the HTTP handler shape
  - backend chosen by PUBSUB_BACKEND in the container
    (container/container.go:80-125)

Backends: MEM (in-process broker — the hermetic seam the reference covers
with mock Reader/Writer interfaces, kafka/interfaces.go:9-25), KAFKA /
GOOGLE / MQTT gated behind their optional client libraries.
"""

from __future__ import annotations

import json
from typing import Any, Callable, Protocol, runtime_checkable

from .. import Health


@runtime_checkable
class Client(Protocol):
    """Publisher + Subscriber + topic admin + health
    (reference interface.go:9-28)."""

    def publish(self, topic: str, message: bytes) -> None: ...
    def subscribe(self, topic: str, timeout: float | None = None) -> "Message | None": ...
    def create_topic(self, name: str) -> None: ...
    def delete_topic(self, name: str) -> None: ...
    def health_check(self) -> Health: ...
    def close(self) -> None: ...


class Message:
    """A consumed message implementing the Request surface
    (reference message.go:8-50)."""

    def __init__(self, topic: str, value: bytes,
                 metadata: dict[str, str] | None = None,
                 committer: Callable[[], None] | None = None):
        self.topic = topic
        self.value = value
        self.metadata = dict(metadata or {})
        self._committer = committer
        self.committed = False

    # -- Request surface ----------------------------------------------------
    def param(self, key: str, default: str = "") -> str:
        return self.metadata.get(key, default)

    def path_param(self, key: str, default: str = "") -> str:
        return self.metadata.get(key, default)

    def header(self, key: str, default: str = "") -> str:
        return self.metadata.get(key, default)

    def host_name(self) -> str:
        return f"pubsub://{self.topic}"

    def bind(self, into: type | None = None) -> Any:
        """JSON-decode the payload, optionally into a dataclass — identical
        contract to the HTTP Request.bind."""
        import dataclasses

        from ...errors import BadRequest

        if not self.value:
            raise BadRequest("message body is empty")
        try:
            data = json.loads(self.value)
        except json.JSONDecodeError as e:
            raise BadRequest(f"invalid JSON message: {e}") from e
        if into is None:
            return data
        if dataclasses.is_dataclass(into):
            if not isinstance(data, dict):
                raise BadRequest("JSON message must be an object")
            names = {f.name for f in dataclasses.fields(into)}
            return into(**{k: v for k, v in data.items() if k in names})
        if callable(into):
            return into(data)
        raise BadRequest(f"cannot bind into {into!r}")

    # -- Committer (reference interface.go Committer) ------------------------
    def commit(self) -> None:
        if self._committer is not None and not self.committed:
            self._committer()
        self.committed = True


class ObservedClient:
    """Decorator adding the four pubsub counters + logs around any backend
    (reference: counters registered at container/container.go:160-165,
    incremented in the drivers, e.g. kafka.go:90-115)."""

    def __init__(self, inner: Client, logger=None, metrics=None):
        self.inner = inner
        self.logger = logger
        self.metrics = metrics

    def _count(self, name: str, topic: str) -> None:
        if self.metrics is not None:
            try:
                self.metrics.increment_counter(name, topic=topic)
            except Exception:
                pass

    def publish(self, topic: str, message: bytes | str | dict) -> None:
        if isinstance(message, dict):
            message = json.dumps(message, default=str).encode()
        elif isinstance(message, str):
            message = message.encode()
        self._count("app_pubsub_publish_total_count", topic)
        self.inner.publish(topic, message)
        self._count("app_pubsub_publish_success_count", topic)
        if self.logger is not None:
            self.logger.debug({"event": "published", "topic": topic,
                               "bytes": len(message)})

    def subscribe(self, topic: str, timeout: float | None = None) -> Message | None:
        return self.inner.subscribe(topic, timeout)

    def create_topic(self, name: str) -> None:
        self.inner.create_topic(name)

    def delete_topic(self, name: str) -> None:
        self.inner.delete_topic(name)

    def health_check(self) -> Health:
        return self.inner.health_check()

    def close(self) -> None:
        self.inner.close()


def new_pubsub_client(backend: str, cfg, logger=None, metrics=None) -> ObservedClient:
    """Backend factory (reference container/container.go:80-125 switch)."""
    backend = backend.upper()
    if backend in ("MEM", "MEMORY"):
        from .mem import MemBroker

        inner: Client = MemBroker(consumer_group=cfg.get_or_default("CONSUMER_ID", "gofr"))
    elif backend == "KAFKA":
        from .kafka import KafkaClient

        inner = KafkaClient(
            brokers=cfg.get_or_default("PUBSUB_BROKER", "localhost:9092"),
            consumer_group=cfg.get_or_default("CONSUMER_ID", "gofr"),
            partition_size=cfg.get_int("PARTITION_SIZE", 0),
            offset=cfg.get_or_default("PUBSUB_OFFSET", "latest"),
            logger=logger)
    elif backend == "GOOGLE":
        from .google import GooglePubSubClient

        inner = GooglePubSubClient(
            project_id=cfg.get("GOOGLE_PROJECT_ID"),
            subscription_name=cfg.get_or_default("GOOGLE_SUBSCRIPTION_NAME", "gofr-sub"),
            logger=logger)
    elif backend == "MQTT":
        from .mqtt import MQTTClient

        inner = MQTTClient(
            broker=cfg.get_or_default("MQTT_HOST", "broker.hivemq.com"),
            port=cfg.get_int("MQTT_PORT", 1883),
            client_id=cfg.get_or_default("MQTT_CLIENT_ID", "gofr-mqtt"),
            qos=cfg.get_int("MQTT_QOS", 0),
            logger=logger)
    else:
        raise ValueError(f"unsupported PUBSUB_BACKEND {backend!r}")
    return ObservedClient(inner, logger, metrics)
