"""Redis datasource: a dependency-free RESP2 client with command observability.

Reference: pkg/gofr/datasource/redis/ —
  - client from REDIS_HOST/PORT (redis.go:35-76)
  - a hook logging every command + pipeline with µs duration into the
    ``app_redis_stats`` histogram (hook.go:65-84)
  - health via PING + INFO Stats (health.go:11-40)

The reference rides go-redis; no Redis client library is available here, so
this speaks the RESP2 wire protocol directly over a socket — which also
keeps the datasource layer dependency-free. The testutil FakeRedisServer
(testutil/redisfake.py) is the miniredis-equivalent seam
(reference datasource/redis/redis_test.go:48-52).
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Any

from . import DSLogger, Health, STATUS_DOWN, STATUS_UP


class RedisError(Exception):
    """Server-side error reply (RESP '-ERR ...')."""


class RedisConnectionLost(ConnectionError):
    """The server closed the connection mid-reply. Subclasses
    ConnectionError so command()'s retry/reconnect arms keep catching
    it, while giving the failure a typed name the wire can map."""


def encode_command(*args: Any) -> bytes:
    """RESP2 array-of-bulk-strings request framing."""
    out = [f"*{len(args)}\r\n".encode()]
    for a in args:
        b = a if isinstance(a, bytes) else str(a).encode()
        out.append(f"${len(b)}\r\n".encode())
        out.append(b)
        out.append(b"\r\n")
    return b"".join(out)


class _Reader:
    """Buffered RESP2 reply parser over a socket."""

    def __init__(self, sock: socket.socket):
        self._sock = sock
        self._buf = b""

    def _read_line(self) -> bytes:
        while b"\r\n" not in self._buf:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise RedisConnectionLost("redis connection closed")
            self._buf += chunk
        line, self._buf = self._buf.split(b"\r\n", 1)
        return line

    def _read_exact(self, n: int) -> bytes:
        while len(self._buf) < n + 2:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise RedisConnectionLost("redis connection closed")
            self._buf += chunk
        data, self._buf = self._buf[:n], self._buf[n + 2:]  # strip \r\n
        return data

    def read_reply(self) -> Any:
        line = self._read_line()
        kind, rest = line[:1], line[1:]
        if kind == b"+":
            return rest.decode()
        if kind == b"-":
            raise RedisError(rest.decode())
        if kind == b":":
            return int(rest)
        if kind == b"$":
            n = int(rest)
            if n == -1:
                return None
            return self._read_exact(n)
        if kind == b"*":
            n = int(rest)
            if n == -1:
                return None
            return [self.read_reply() for _ in range(n)]
        raise RedisError(f"unexpected RESP type {line!r}")


class Pipeline:
    """Batched commands flushed in one round trip
    (reference hook.go:75-84 ProcessPipelineHook observes the whole batch)."""

    def __init__(self, client: "RedisClient"):
        self._client = client
        self._cmds: list[tuple] = []

    def command(self, *args) -> "Pipeline":
        self._cmds.append(args)
        return self

    def __getattr__(self, name: str):
        def call(*args):
            return self.command(name.upper(), *args)
        return call

    def execute(self) -> list[Any]:
        return self._client._execute_pipeline(self._cmds)


class RedisClient:
    def __init__(self, host: str = "localhost", port: int = 6379,
                 logger: DSLogger | None = None, metrics=None,
                 timeout: float = 5.0):
        self.host = host
        self.port = port
        self.logger = logger
        self.metrics = metrics
        self.timeout = timeout
        self._io_lock = threading.Lock()
        self._sock: socket.socket | None = None
        self._reader: _Reader | None = None
        self._connect()

    def _connect(self) -> None:
        self._sock = socket.create_connection((self.host, self.port),
                                              timeout=self.timeout)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._reader = _Reader(self._sock)

    # -- observability hook (reference hook.go:65-84) ------------------------
    def _observe(self, label: str, dur_us: float) -> None:
        if self.metrics is not None:
            try:
                self.metrics.record_histogram("app_redis_stats", dur_us, type=label)
            except Exception:
                pass
        if self.logger is not None:
            self.logger.debug({"event": "redis command", "command": label,
                               "duration_us": int(dur_us)})

    # -- generic command ----------------------------------------------------
    def command(self, *args) -> Any:
        label = str(args[0]).upper() if args else ""
        start = time.perf_counter()
        payload = encode_command(*args)
        with self._io_lock:
            try:
                self._sock.sendall(payload)
            except (ConnectionError, OSError, AttributeError):
                # safe to retry: the command never reached the server
                # (AttributeError: socket already closed -> _sock is None)
                self._connect()
                self._sock.sendall(payload)
            try:
                reply = self._reader.read_reply()
            except (ConnectionError, OSError):
                # NOT safe to blindly resend (the server may have executed a
                # non-idempotent command before the connection died) — but we
                # must reconnect so the stream isn't left desynchronized
                self._connect()
                raise
        self._observe(label, (time.perf_counter() - start) * 1e6)
        return reply

    def _execute_pipeline(self, cmds: list[tuple]) -> list[Any]:
        if not cmds:
            return []
        start = time.perf_counter()
        payload = b"".join(encode_command(*c) for c in cmds)
        with self._io_lock:
            try:
                self._sock.sendall(payload)
                replies = []
                for _ in cmds:
                    try:
                        replies.append(self._reader.read_reply())
                    except RedisError as e:
                        replies.append(e)
            except (ConnectionError, OSError):
                # reconnect so leftover in-flight replies can't be read as
                # answers to later commands, then surface the failure
                self._connect()
                raise
        self._observe(f"pipeline[{len(cmds)}]", (time.perf_counter() - start) * 1e6)
        return replies

    def pipeline(self) -> Pipeline:
        return Pipeline(self)

    # -- typed convenience surface ------------------------------------------
    @staticmethod
    def _text(reply: Any) -> str | None:
        return reply.decode() if isinstance(reply, bytes) else reply

    def ping(self) -> bool:
        return self.command("PING") == "PONG"

    def set(self, key: str, value: Any, ex: float | None = None) -> bool:
        args: list[Any] = ["SET", key, value]
        if ex is not None:
            args += ["PX", int(ex * 1000)]
        return self.command(*args) == "OK"

    def get(self, key: str) -> str | None:
        return self._text(self.command("GET", key))

    def get_bytes(self, key: str) -> bytes | None:
        """GET returning the raw bulk-string payload. ``get`` decodes
        replies to ``str``, which is lossy for binary values (KV cache
        blocks, packed structs, pickles) — this keeps the bytes."""
        reply = self.command("GET", key)
        if reply is None or isinstance(reply, bytes):
            return reply
        return str(reply).encode()

    def mget(self, *keys: str) -> list[bytes | None]:
        """MGET returning raw ``bytes`` per key (None for absent keys)
        — one round trip for a whole block chain; binary-safe like
        ``get_bytes``."""
        if not keys:
            return []
        out = []
        for reply in self.command("MGET", *keys) or []:
            if reply is None or isinstance(reply, bytes):
                out.append(reply)
            else:
                out.append(str(reply).encode())
        return out

    def delete(self, *keys: str) -> int:
        return self.command("DEL", *keys)

    def exists(self, *keys: str) -> int:
        return self.command("EXISTS", *keys)

    def incr(self, key: str, by: int = 1) -> int:
        return self.command("INCRBY", key, by)

    def decr(self, key: str, by: int = 1) -> int:
        return self.command("DECRBY", key, by)

    def expire(self, key: str, seconds: float) -> bool:
        return self.command("PEXPIRE", key, int(seconds * 1000)) == 1

    def ttl(self, key: str) -> int:
        return self.command("TTL", key)

    def keys(self, pattern: str = "*") -> list[str]:
        return [self._text(k) for k in self.command("KEYS", pattern)]

    def hset(self, key: str, field: str, value: Any, *more) -> int:
        return self.command("HSET", key, field, value, *more)

    def hget(self, key: str, field: str) -> str | None:
        return self._text(self.command("HGET", key, field))

    def hgetall(self, key: str) -> dict[str, str]:
        flat = self.command("HGETALL", key) or []
        it = iter(flat)
        return {self._text(k): self._text(v) for k, v in zip(it, it)}

    def hdel(self, key: str, *fields: str) -> int:
        return self.command("HDEL", key, *fields)

    def lpush(self, key: str, *values) -> int:
        return self.command("LPUSH", key, *values)

    def rpush(self, key: str, *values) -> int:
        return self.command("RPUSH", key, *values)

    def lrange(self, key: str, start: int = 0, stop: int = -1) -> list[str]:
        return [self._text(v) for v in self.command("LRANGE", key, start, stop)]

    def flushdb(self) -> bool:
        return self.command("FLUSHDB") == "OK"

    def info(self, section: str = "") -> dict[str, str]:
        raw = self.command("INFO", section) if section else self.command("INFO")
        out: dict[str, str] = {}
        for line in (self._text(raw) or "").splitlines():
            if line and not line.startswith("#") and ":" in line:
                k, v = line.split(":", 1)
                out[k] = v
        return out

    # -- health (reference health.go:11-40) ----------------------------------
    def health_check(self) -> Health:
        try:
            stats = self.info("stats")
            return Health(status=STATUS_UP, details={
                "host": f"{self.host}:{self.port}", **stats})
        except Exception as e:
            return Health(status=STATUS_DOWN, details={
                "host": f"{self.host}:{self.port}", "error": repr(e)})

    def close(self) -> None:
        with self._io_lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                except Exception:
                    pass
                self._sock = None


def new_redis_client(cfg, logger: DSLogger | None = None, metrics=None) -> RedisClient:
    """Wire from config (reference redis.go:38-47): REDIS_HOST, REDIS_PORT."""
    return RedisClient(
        host=cfg.get_or_default("REDIS_HOST", "localhost"),
        port=cfg.get_int("REDIS_PORT", 6379),
        logger=logger, metrics=metrics)
