"""Self-contained distributed tracing with W3C traceparent propagation.

Reference wiring: OTel tracer provider + composite propagator at startup
(pkg/gofr/gofr.go:235-243), inbound span per request
(http/middleware/tracer.go:14-30), handler span (handler.go:34), user spans via
``c.Trace(name)`` (context.go:45-51), outbound header injection
(service/new.go:140-158), optional Zipkin batch exporter (gofr.go:245-257).

This implementation is dependency-free: spans are kept in a contextvar stack,
trace context crosses process boundaries via the ``traceparent`` header
(W3C Trace Context, same wire format the reference propagates), and finished
spans go to a pluggable exporter (a Zipkin-JSON HTTP exporter is provided).
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import secrets
import threading
import time
import urllib.request
from dataclasses import dataclass, field
from typing import Any, Callable

_current: contextvars.ContextVar["Span | None"] = contextvars.ContextVar(
    "gofr_tpu_current_span", default=None
)


def _new_trace_id() -> str:
    return secrets.token_hex(16)


def _new_span_id() -> str:
    return secrets.token_hex(8)


@dataclass
class Span:
    name: str
    trace_id: str
    span_id: str
    parent_id: str | None = None
    start_ns: int = field(default_factory=time.monotonic_ns)
    start_epoch_us: int = field(default_factory=lambda: int(time.time() * 1e6))
    end_ns: int | None = None
    attributes: dict[str, Any] = field(default_factory=dict)
    tracer: "Tracer | None" = None
    _token: Any = None

    def set_attribute(self, key: str, value: Any) -> None:
        self.attributes[key] = value

    def end(self) -> None:
        if self.end_ns is None:
            self.end_ns = time.monotonic_ns()
            if self.tracer is not None:
                self.tracer._on_end(self)

    @property
    def duration_us(self) -> int:
        end = self.end_ns if self.end_ns is not None else time.monotonic_ns()
        return (end - self.start_ns) // 1000

    def traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-01"


def current_span() -> Span | None:
    return _current.get()


def parse_traceparent(header: str | None) -> tuple[str, str] | None:
    """Parse a W3C traceparent header -> (trace_id, parent_span_id)."""
    if not header:
        return None
    parts = header.strip().split("-")
    if len(parts) != 4:
        return None
    _, trace_id, span_id, _ = parts
    if len(trace_id) != 32 or len(span_id) != 16:
        return None
    try:
        tid, sid = int(trace_id, 16), int(span_id, 16)
    except ValueError:
        return None
    if tid == 0 or sid == 0:
        # W3C Trace Context: all-zero trace-id/parent-id are invalid
        # values; propagating them would stitch unrelated requests into
        # one "trace 000..0". Treat as absent — start a fresh trace.
        return None
    return trace_id, span_id


class Tracer:
    """Creates spans and hands finished ones to the exporter."""

    def __init__(self, service_name: str = "gofr-app", exporter: "SpanExporter | None" = None):
        self.service_name = service_name
        self.exporter = exporter

    def start_span(
        self,
        name: str,
        *,
        parent: Span | None = None,
        traceparent: str | None = None,
        attributes: dict[str, Any] | None = None,
    ) -> Span:
        if parent is None:
            parent = current_span()
        if parent is not None:
            trace_id, parent_id = parent.trace_id, parent.span_id
        else:
            ctx = parse_traceparent(traceparent)
            if ctx is not None:
                trace_id, parent_id = ctx
            else:
                trace_id, parent_id = _new_trace_id(), None
        span = Span(
            name=name,
            trace_id=trace_id,
            span_id=_new_span_id(),
            parent_id=parent_id,
            attributes=dict(attributes or {}),
            tracer=self,
        )
        span._token = _current.set(span)
        return span

    def _on_end(self, span: Span) -> None:
        if span._token is not None:
            with contextlib.suppress(ValueError):
                _current.reset(span._token)
            span._token = None
        if self.exporter is not None:
            self.exporter.export(span, self.service_name)

    @contextlib.contextmanager
    def span(self, name: str, **kw: Any):
        s = self.start_span(name, **kw)
        try:
            yield s
        finally:
            s.end()

    def record_span(
        self,
        name: str,
        start_monotonic: float,
        end_monotonic: float,
        *,
        traceparent: str | None = None,
        trace_id: str | None = None,
        attributes: dict[str, Any] | None = None,
    ) -> Span:
        """Export a span for an interval measured elsewhere (serving-loop
        stage timings: admit wait, prefill, decode). Unlike start_span
        this never touches the contextvar stack — the serving loop is
        one thread multiplexing every request, so "current span" is
        meaningless there — and the span arrives already finished.

        ``trace_id`` correlates spans without claiming a parent: when no
        valid ``traceparent`` exists, the span joins that trace as a
        root instead of pointing at a phantom parent span id."""
        ctx = parse_traceparent(traceparent)
        if ctx is not None:
            trace_id, parent_id = ctx
        else:
            trace_id, parent_id = trace_id or _new_trace_id(), None
        now_mono, now_epoch = time.monotonic(), time.time()
        span = Span(
            name=name,
            trace_id=trace_id,
            span_id=_new_span_id(),
            parent_id=parent_id,
            start_ns=int(start_monotonic * 1e9),
            start_epoch_us=int((now_epoch - (now_mono - start_monotonic)) * 1e6),
            attributes=dict(attributes or {}),
        )
        span.end_ns = int(end_monotonic * 1e9)
        if self.exporter is not None:
            self.exporter.export(span, self.service_name)
        return span


class SpanExporter:
    def export(self, span: Span, service_name: str) -> None:  # pragma: no cover
        raise NotImplementedError

    def shutdown(self) -> None:
        pass


class InMemoryExporter(SpanExporter):
    """Test exporter collecting finished spans."""

    def __init__(self) -> None:
        self.spans: list[Span] = []

    def export(self, span: Span, service_name: str) -> None:
        self.spans.append(span)


class ZipkinExporter(SpanExporter):
    """Batched Zipkin v2 JSON exporter (reference: gofr.go:245-257 wires a
    zipkin batch exporter when TRACER_HOST is set)."""

    def __init__(self, host: str, port: int = 9411, batch_size: int = 64,
                 flush_interval: float = 2.0):
        self.url = f"http://{host}:{port}/api/v2/spans"
        self.batch_size = batch_size
        self.flush_interval = flush_interval
        self._buf: list[dict] = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._wake = threading.Event()  # full batch -> flush thread, now
        self._thread = threading.Thread(target=self._loop, daemon=True, name="zipkin-exporter")
        self._thread.start()

    def export(self, span: Span, service_name: str) -> None:
        z = {
            "traceId": span.trace_id,
            "id": span.span_id,
            "name": span.name,
            "timestamp": span.start_epoch_us,
            "duration": max(span.duration_us, 1),
            "localEndpoint": {"serviceName": service_name},
            "tags": {k: str(v) for k, v in span.attributes.items()},
        }
        if span.parent_id:
            z["parentId"] = span.parent_id
        flush_now = False
        with self._lock:
            self._buf.append(z)
            if len(self._buf) >= self.batch_size:
                flush_now = True
        if flush_now:
            # hand the POST to the flush thread instead of doing it here:
            # export() is called from request handlers AND the generation
            # serving loop, and a slow collector must never block either
            # (a 2 s urlopen on the loop thread would stall every stream)
            self._wake.set()

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(self.flush_interval)
            self._wake.clear()
            self._flush()

    def _flush(self) -> None:
        with self._lock:
            batch, self._buf = self._buf, []
        if not batch:
            return
        try:
            req = urllib.request.Request(
                self.url,
                data=json.dumps(batch).encode(),
                headers={"Content-Type": "application/json"},
            )
            urllib.request.urlopen(req, timeout=2).close()
        except Exception:
            pass  # tracing must never take the app down

    def shutdown(self) -> None:
        """Final flush on graceful shutdown. Joining the flush thread
        matters: without it a clean exit could tear the interpreter down
        mid-POST and silently drop the last batch of spans."""
        self._stop.set()
        self._wake.set()  # unblock the interval wait immediately
        self._thread.join(timeout=5.0)
        self._flush()


def tracer_from_config(config, service_name: str) -> Tracer:
    """Reference: gofr.go:231-258 initTracer — exporter only when TRACER_HOST set."""
    host = config.get("TRACER_HOST")
    exporter: SpanExporter | None = None
    if host:
        port = int(config.get_or_default("TRACER_PORT", "9411"))
        exporter = ZipkinExporter(host, port)
    return Tracer(service_name=service_name, exporter=exporter)


NoopSpan = Span(name="noop", trace_id="0" * 32, span_id="0" * 16)
Callable  # re-export quiet
