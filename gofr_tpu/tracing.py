"""Self-contained distributed tracing with W3C traceparent propagation.

Reference wiring: OTel tracer provider + composite propagator at startup
(pkg/gofr/gofr.go:235-243), inbound span per request
(http/middleware/tracer.go:14-30), handler span (handler.go:34), user spans via
``c.Trace(name)`` (context.go:45-51), outbound header injection
(service/new.go:140-158), optional Zipkin batch exporter (gofr.go:245-257).

This implementation is dependency-free: spans are kept in a contextvar stack,
trace context crosses process boundaries via the ``traceparent`` header
(W3C Trace Context, same wire format the reference propagates), and finished
spans go to a pluggable exporter (a Zipkin-JSON HTTP exporter is provided).
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import secrets
import threading
import time
import urllib.request
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Any, Callable

_current: contextvars.ContextVar["Span | None"] = contextvars.ContextVar(
    "gofr_tpu_current_span", default=None
)


def _new_trace_id() -> str:
    return secrets.token_hex(16)


def _new_span_id() -> str:
    return secrets.token_hex(8)


@dataclass
class Span:
    name: str
    trace_id: str
    span_id: str
    parent_id: str | None = None
    start_ns: int = field(default_factory=time.monotonic_ns)
    start_epoch_us: int = field(default_factory=lambda: int(time.time() * 1e6))
    end_ns: int | None = None
    attributes: dict[str, Any] = field(default_factory=dict)
    tracer: "Tracer | None" = None
    # the process-LOCAL root of its trace: the first span a request
    # opens in this process (HTTP/gRPC inbound middleware). Tail-based
    # sampling buffers a trace until its root finishes, then judges the
    # whole trace at once; record_span intervals never root.
    root: bool = False
    _token: Any = None

    def set_attribute(self, key: str, value: Any) -> None:
        self.attributes[key] = value

    def end(self) -> None:
        if self.end_ns is None:
            self.end_ns = time.monotonic_ns()
            if self.tracer is not None:
                self.tracer._on_end(self)

    @property
    def duration_us(self) -> int:
        end = self.end_ns if self.end_ns is not None else time.monotonic_ns()
        return (end - self.start_ns) // 1000

    def traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-01"


def current_span() -> Span | None:
    return _current.get()


def parse_traceparent(header: str | None) -> tuple[str, str] | None:
    """Parse a W3C traceparent header -> (trace_id, parent_span_id)."""
    if not header:
        return None
    parts = header.strip().split("-")
    if len(parts) != 4:
        return None
    _, trace_id, span_id, _ = parts
    if len(trace_id) != 32 or len(span_id) != 16:
        return None
    try:
        tid, sid = int(trace_id, 16), int(span_id, 16)
    except ValueError:
        return None
    if tid == 0 or sid == 0:
        # W3C Trace Context: all-zero trace-id/parent-id are invalid
        # values; propagating them would stitch unrelated requests into
        # one "trace 000..0". Treat as absent — start a fresh trace.
        return None
    return trace_id, span_id


class Tracer:
    """Creates spans and hands finished ones to the exporter."""

    def __init__(self, service_name: str = "gofr-app", exporter: "SpanExporter | None" = None):
        self.service_name = service_name
        self.exporter = exporter

    def start_span(
        self,
        name: str,
        *,
        parent: Span | None = None,
        traceparent: str | None = None,
        attributes: dict[str, Any] | None = None,
    ) -> Span:
        if parent is None:
            parent = current_span()
        if parent is not None:
            trace_id, parent_id = parent.trace_id, parent.span_id
        else:
            ctx = parse_traceparent(traceparent)
            if ctx is not None:
                trace_id, parent_id = ctx
            else:
                trace_id, parent_id = _new_trace_id(), None
        span = Span(
            name=name,
            trace_id=trace_id,
            span_id=_new_span_id(),
            parent_id=parent_id,
            attributes=dict(attributes or {}),
            tracer=self,
            # no AMBIENT parent -> this is the process-local root of
            # its trace (an inbound traceparent makes it a child in the
            # distributed trace but still the root HERE, which is the
            # scope a per-process tail sampler can judge)
            root=parent is None,
        )
        span._token = _current.set(span)
        return span

    def _on_end(self, span: Span) -> None:
        if span._token is not None:
            with contextlib.suppress(ValueError):
                _current.reset(span._token)
            span._token = None
        if self.exporter is not None:
            self.exporter.export(span, self.service_name)

    @contextlib.contextmanager
    def span(self, name: str, **kw: Any):
        s = self.start_span(name, **kw)
        try:
            yield s
        finally:
            s.end()

    def record_span(
        self,
        name: str,
        start_monotonic: float,
        end_monotonic: float,
        *,
        traceparent: str | None = None,
        trace_id: str | None = None,
        attributes: dict[str, Any] | None = None,
    ) -> Span:
        """Export a span for an interval measured elsewhere (serving-loop
        stage timings: admit wait, prefill, decode). Unlike start_span
        this never touches the contextvar stack — the serving loop is
        one thread multiplexing every request, so "current span" is
        meaningless there — and the span arrives already finished.

        ``trace_id`` correlates spans without claiming a parent: when no
        valid ``traceparent`` exists, the span joins that trace as a
        root instead of pointing at a phantom parent span id."""
        ctx = parse_traceparent(traceparent)
        if ctx is not None:
            trace_id, parent_id = ctx
        else:
            trace_id, parent_id = trace_id or _new_trace_id(), None
        now_mono, now_epoch = time.monotonic(), time.time()
        span = Span(
            name=name,
            trace_id=trace_id,
            span_id=_new_span_id(),
            parent_id=parent_id,
            start_ns=int(start_monotonic * 1e9),
            start_epoch_us=int((now_epoch - (now_mono - start_monotonic)) * 1e6),
            attributes=dict(attributes or {}),
        )
        span.end_ns = int(end_monotonic * 1e9)
        if self.exporter is not None:
            self.exporter.export(span, self.service_name)
        return span


class SpanExporter:
    def export(self, span: Span, service_name: str) -> None:  # pragma: no cover
        raise NotImplementedError

    def shutdown(self) -> None:
        pass


class InMemoryExporter(SpanExporter):
    """Test exporter collecting finished spans."""

    def __init__(self) -> None:
        self.spans: list[Span] = []

    def export(self, span: Span, service_name: str) -> None:
        self.spans.append(span)


class ZipkinExporter(SpanExporter):
    """Batched Zipkin v2 JSON exporter (reference: gofr.go:245-257 wires a
    zipkin batch exporter when TRACER_HOST is set).

    The pending buffer is BOUNDED (``max_pending``): with the collector
    down or stalled, fail-open export must cost bounded memory, not an
    unbounded list growing one dict per span for the outage's duration.
    On overflow the OLDEST pending spans drop (the newest are the ones
    an operator triages) and ``dropped`` / the
    ``app_tpu_spans_dropped_total`` counter record how many."""

    def __init__(self, host: str, port: int = 9411, batch_size: int = 64,
                 flush_interval: float = 2.0, max_pending: int = 4096,
                 metrics=None):
        self.url = f"http://{host}:{port}/api/v2/spans"
        self.batch_size = batch_size
        self.flush_interval = flush_interval
        self.max_pending = max(1, int(max_pending))
        self.metrics = metrics
        self.dropped = 0
        self._buf: deque[dict] = deque()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._wake = threading.Event()  # full batch -> flush thread, now
        self._thread = threading.Thread(target=self._loop, daemon=True, name="zipkin-exporter")
        self._thread.start()

    def export(self, span: Span, service_name: str) -> None:
        z = {
            "traceId": span.trace_id,
            "id": span.span_id,
            "name": span.name,
            "timestamp": span.start_epoch_us,
            "duration": max(span.duration_us, 1),
            "localEndpoint": {"serviceName": service_name},
            "tags": {k: str(v) for k, v in span.attributes.items()},
        }
        if span.parent_id:
            z["parentId"] = span.parent_id
        flush_now = False
        n_dropped = 0
        with self._lock:
            self._buf.append(z)
            while len(self._buf) > self.max_pending:
                self._buf.popleft()
                self.dropped += 1
                n_dropped += 1
            if len(self._buf) >= self.batch_size:
                flush_now = True
        if n_dropped and self.metrics is not None:
            try:
                for _ in range(n_dropped):
                    self.metrics.increment_counter(
                        "app_tpu_spans_dropped_total")
            except Exception:
                pass  # tracing must never take the app down
        if flush_now:
            # hand the POST to the flush thread instead of doing it here:
            # export() is called from request handlers AND the generation
            # serving loop, and a slow collector must never block either
            # (a 2 s urlopen on the loop thread would stall every stream)
            self._wake.set()

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(self.flush_interval)
            self._wake.clear()
            self._flush()

    def _flush(self) -> None:
        with self._lock:
            batch, self._buf = list(self._buf), deque()
        if not batch:
            return
        try:
            req = urllib.request.Request(
                self.url,
                data=json.dumps(batch).encode(),
                headers={"Content-Type": "application/json"},
            )
            urllib.request.urlopen(req, timeout=2).close()
        except Exception:
            pass  # tracing must never take the app down

    def shutdown(self) -> None:
        """Final flush on graceful shutdown. Joining the flush thread
        matters: without it a clean exit could tear the interpreter down
        mid-POST and silently drop the last batch of spans."""
        self._stop.set()
        self._wake.set()  # unblock the interval wait immediately
        self._thread.join(timeout=5.0)
        self._flush()


class TailSampler(SpanExporter):
    """Tail-based sampling: buffer each trace until its process-local
    ROOT span finishes, then judge the whole trace at once.

    Export-everything tracing drowns the spans that matter: at serving
    rates the collector stores millions of healthy request traces to
    keep the handful that shed, expired, errored, or landed in the
    latency tail. The verdict here keeps 100% of:

      - error traces — any span with an ``error`` attribute, a non-OK
        ``rpc.grpc.status_code``, or ``http.status_code`` >= 429 (429
        = shed, 504 = deadline exceeded, 5xx = failure);
      - shed/expired traces — the gate's zero-length ``tpu.shed``
        marker span, or an ``expired``/``shed`` outcome attribute;
      - slow-tail traces — root latency above a rolling per-class p99
        estimate (the last ``window`` roots of that ``slo_class``);

    and samples the healthy rest at ``sample_rate`` — DETERMINISTIC in
    the trace id (a hash-fraction compare), so every process in a fleet
    keeps or drops the same distributed trace. Traces whose root never
    arrives in this process (engine-direct ``generate()`` stage spans)
    are judged after ``linger_s`` by the same rules minus the root
    latency. Once judged, late spans of the same trace follow the
    recorded verdict instead of re-buffering."""

    def __init__(self, downstream: SpanExporter, sample_rate: float = 1.0,
                 max_traces: int = 512, max_spans_per_trace: int = 256,
                 linger_s: float = 5.0, window: int = 256,
                 min_samples: int = 20, metrics=None):
        self.downstream = downstream
        self.metrics = metrics
        self.sample_rate = min(1.0, max(0.0, float(sample_rate)))
        self.max_traces = int(max_traces)
        self.max_spans_per_trace = int(max_spans_per_trace)
        self.linger_s = float(linger_s)
        self.min_samples = int(min_samples)
        self._lock = threading.Lock()
        # trace_id -> [first_seen_monotonic, [spans], interesting, service]
        self._pending: "OrderedDict[str, list]" = OrderedDict()
        # decided traces (bounded LRU): late spans follow the verdict
        self._verdicts: "OrderedDict[str, bool]" = OrderedDict()
        self._lat: dict[str, deque] = {}
        self._lat_sorted: dict[str, list | None] = {}
        self._window = int(window)
        self.kept_traces = 0
        self.dropped_traces = 0
        self.spans_truncated = 0  # per-trace span-cap overflow (visible)
        # keep verdicts by WHY (the drop rate alone can't distinguish
        # "sampling works" from "nothing interesting ever fires")
        self.kept_by_reason = {"interesting": 0, "slow": 0, "sampled": 0}
        self.linger_sweeps = 0  # sweeps that judged >=1 rootless trace
        # idle flush: the sweep otherwise only runs inside export(), so
        # a process whose span traffic STOPS would strand its buffered
        # rootless traces (including error traces) forever. A daemon
        # timer sweeps on the linger cadence; started lazily on first
        # export so a sampler built in tests costs no thread until used.
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- verdict inputs ------------------------------------------------------
    @staticmethod
    def interesting(span: Span) -> bool:
        """Must-keep signal on a single span."""
        if span.name == "tpu.shed":
            return True
        attrs = span.attributes
        if "error" in attrs:
            return True
        if str(attrs.get("outcome", "")) in ("shed", "expired", "failed"):
            return True
        grpc = attrs.get("rpc.grpc.status_code")
        if grpc is not None:
            try:
                if int(grpc) != 0:
                    return True
            except (TypeError, ValueError):
                return True
        http = attrs.get("http.status_code")
        if http is not None:
            try:
                if int(http) >= 429:
                    return True
            except (TypeError, ValueError):
                pass
        return False

    def _sampled(self, trace_id: str) -> bool:
        """Deterministic hash-fraction sample on the trace id."""
        if self.sample_rate >= 1.0:
            return True
        if self.sample_rate <= 0.0:
            return False
        try:
            frac = int(trace_id[:13], 16) / float(16 ** 13)
        except (TypeError, ValueError):
            return True  # unparseable id: fail open, keep
        return frac < self.sample_rate

    def _p99(self, slo_class: str) -> float | None:
        d = self._lat.get(slo_class)
        if d is None or len(d) < self.min_samples:
            return None  # estimator still warming: no slow-tail verdict
        s = self._lat_sorted.get(slo_class)
        if s is None:
            # sorted view cached until the next sample: every span
            # export serializes behind this lock, so an O(n log n)
            # sort per ROOT (not per read) is the budget
            s = self._lat_sorted[slo_class] = sorted(d)
        return s[min(len(s) - 1, int(0.99 * len(s)))]

    def _note_latency(self, slo_class: str, dur_s: float) -> None:
        d = self._lat.get(slo_class)
        if d is None:
            d = self._lat[slo_class] = deque(maxlen=self._window)
        d.append(dur_s)
        self._lat_sorted[slo_class] = None  # invalidate the cached sort

    # -- exporter protocol ---------------------------------------------------
    def _ensure_sweeper(self) -> None:
        """Start the idle-flush thread (once): without it, buffered
        rootless traces would only ever be judged by a LATER export —
        and a process whose traffic stops never makes one."""
        if self._thread is not None:
            return
        with self._lock:
            if self._thread is None and not self._stop.is_set():
                self._thread = threading.Thread(target=self._sweep_loop,
                                                daemon=True,
                                                name="tail-sampler")
                self._thread.start()

    def _sweep_loop(self) -> None:
        interval = max(0.25, self.linger_s or 1.0)
        while not self._stop.wait(interval):
            with self._lock:
                to_flush = self._sweep_locked()
            for s, svc in to_flush:
                try:
                    self.downstream.export(s, svc)
                except Exception:
                    pass  # tracing must never take the app down

    def export(self, span: Span, service_name: str) -> None:
        self._ensure_sweeper()
        to_flush: list[tuple[Span, str]] = []
        with self._lock:
            verdict = self._verdicts.get(span.trace_id)
            if verdict is not None:
                self._verdicts.move_to_end(span.trace_id)
                if not verdict and span.root and self._root_keeps(span):
                    # the linger sweep judged this trace from its
                    # buffered spans while the root was STILL OPEN (a
                    # request longer than linger_s), and the root now
                    # proves it error/slow. The swept spans are gone,
                    # but the root — the span carrying status, duration
                    # and slo_class — must not be: flip the verdict so
                    # it and any later spans export.
                    self._verdicts[span.trace_id] = verdict = True
                    self._note_kept("interesting" if self.interesting(span)
                                    else "slow")
                    self.dropped_traces -= 1
                if verdict:
                    to_flush.append((span, service_name))
            else:
                entry = self._pending.get(span.trace_id)
                if entry is None:
                    entry = [time.monotonic(), [], False, service_name]
                    self._pending[span.trace_id] = entry
                else:
                    # linger measures IDLE time: an active trace that
                    # keeps emitting spans is a live request, not an
                    # orphan to sweep
                    entry[0] = time.monotonic()
                if len(entry[1]) < self.max_spans_per_trace or span.root:
                    # the root always buffers (it may exceed the cap by
                    # one) — a kept trace without its root span would
                    # lose the status/duration the verdict hinged on
                    entry[1].append(span)
                else:
                    self.spans_truncated += 1
                entry[2] = entry[2] or self.interesting(span)
                if span.root:
                    to_flush.extend(self._decide_locked(span.trace_id, span))
                to_flush.extend(self._sweep_locked())
        for s, svc in to_flush:
            self.downstream.export(s, svc)

    def _root_keeps(self, root: Span) -> bool:
        """Late must-keep check for a root whose trace was already
        judged: interesting on its own, or slow-tail vs the rolling
        per-class estimate (which it also feeds)."""
        keep = self.interesting(root)
        dur_s = root.duration_us / 1e6
        cls = str(root.attributes.get("slo_class") or "latency")
        thresh = self._p99(cls)
        if not keep and thresh is not None and dur_s > thresh:
            keep = True
        self._note_latency(cls, dur_s)
        return keep

    def _decide_locked(self, trace_id: str,
                       root: Span | None) -> list[tuple[Span, str]]:
        entry = self._pending.pop(trace_id, None)
        if entry is None:
            return []
        _, spans, is_interesting, service = entry
        keep = is_interesting
        reason = "interesting" if keep else None
        if root is not None:
            dur_s = root.duration_us / 1e6
            cls = str(root.attributes.get("slo_class") or "latency")
            thresh = self._p99(cls)
            if not keep and thresh is not None and dur_s > thresh:
                keep = True  # slow tail: above the rolling per-class p99
                reason = "slow"
            # feed the estimator AFTER judging: a burst of slow roots
            # must not raise the bar fast enough to hide its own tail
            self._note_latency(cls, dur_s)
        if not keep:
            keep = self._sampled(trace_id)
            reason = "sampled" if keep else None
        self._verdicts[trace_id] = keep
        while len(self._verdicts) > 4096:
            self._verdicts.popitem(last=False)
        if keep:
            self._note_kept(reason or "interesting")
            return [(s, service) for s in spans]
        self.dropped_traces += 1
        self._count("app_tpu_trace_dropped_total")
        return []

    def _note_kept(self, reason: str) -> None:
        self.kept_traces += 1
        self.kept_by_reason[reason] = self.kept_by_reason.get(reason, 0) + 1
        self._count("app_tpu_trace_kept_total", reason=reason)

    def _count(self, name: str, **labels) -> None:
        if self.metrics is None:
            return
        try:
            self.metrics.increment_counter(name, **labels)
        except Exception:
            pass  # telemetry must never take the sampler down

    def _sweep_locked(self, force: bool = False) -> list[tuple[Span, str]]:
        """Judge rootless traces past the linger window (and evict by
        count): a trace whose root never reaches this process still
        gets a verdict from its buffered spans alone."""
        out: list[tuple[Span, str]] = []
        now = time.monotonic()
        judged = False
        while self._pending:
            oldest_id, entry = next(iter(self._pending.items()))
            stale = force or (now - entry[0]) >= self.linger_s \
                or len(self._pending) > self.max_traces
            if not stale:
                break
            out.extend(self._decide_locked(oldest_id, None))
            judged = True
        if judged:
            self.linger_sweeps += 1
            self._count("app_tpu_trace_sweeps_total")
        return out

    def flush_pending(self) -> None:
        """Judge every buffered trace now (tests, shutdown)."""
        with self._lock:
            to_flush = self._sweep_locked(force=True)
        for s, svc in to_flush:
            self.downstream.export(s, svc)

    def stats(self) -> dict:
        with self._lock:
            return {
                "sample_rate": self.sample_rate,
                "pending_traces": len(self._pending),
                "kept_traces": self.kept_traces,
                "kept_by_reason": dict(self.kept_by_reason),
                "dropped_traces": self.dropped_traces,
                "spans_truncated": self.spans_truncated,
                "linger_sweeps": self.linger_sweeps,
            }

    def shutdown(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        self.flush_pending()
        self.downstream.shutdown()


def tracer_from_config(config, service_name: str, metrics=None) -> Tracer:
    """Reference: gofr.go:231-258 initTracer — exporter only when
    TRACER_HOST is set. The exporter is wrapped in a TailSampler:
    ``TPU_TRACE_SAMPLE`` is the keep rate for HEALTHY traces (default
    1.0 = keep everything; shed/expired/error/slow-tail traces are
    always kept regardless)."""
    host = config.get("TRACER_HOST")
    exporter: SpanExporter | None = None
    if host:
        port = int(config.get_or_default("TRACER_PORT", "9411"))
        exporter = ZipkinExporter(host, port, metrics=metrics)
        try:
            rate = float(config.get("TPU_TRACE_SAMPLE") or 1.0)
        except (TypeError, ValueError):
            rate = 1.0
        try:
            linger = float(config.get("TPU_TRACE_TAIL_LINGER_S") or 5.0)
        except (TypeError, ValueError):
            linger = 5.0
        exporter = TailSampler(exporter, sample_rate=rate, linger_s=linger,
                               metrics=metrics)
    return Tracer(service_name=service_name, exporter=exporter)


NoopSpan = Span(name="noop", trace_id="0" * 32, span_id="0" * 16)
Callable  # re-export quiet
