"""Disaggregated prefill/decode serving (docs/advanced-guide/
disaggregated-serving.md).

Prefill is compute-bound and bursty; decode is memory-bound and steady.
``TPU_SERVING_ROLE`` splits them into dedicated pools that scale
independently: **prefill workers** compute prompt KV and ship it as
checksummed int8 block frames (the ``tpu/kvcache/quant.py`` codec)
over a ``wire.py``-backed stream to **decode workers**, which own the
slot lattice and the token stream. Each pool draws its own HBM-arbiter
budget with its own reclaim policy; deadlines, SLO classes and W3C
trace context cross the boundary with the request.

``wire_role`` is the config seam: called by ``new_engine_from_config``
when ``TPU_SERVING_ROLE`` is ``prefill`` or ``decode`` (``fused``, the
default, wires nothing and serves exactly as before).
"""

from __future__ import annotations

from .ingest import KVIngestServer
from .prefill import PDPrefill, RelayStream
from .protocol import DecodePeerUnavailable, KVTransferError

__all__ = ["DecodePeerUnavailable", "KVIngestServer", "KVTransferError",
           "PDPrefill", "ROLES", "RelayStream", "parse_role", "wire_role"]

ROLE_FUSED = "fused"
ROLE_PREFILL = "prefill"
ROLE_DECODE = "decode"
ROLES = (ROLE_FUSED, ROLE_PREFILL, ROLE_DECODE)

DEFAULT_LISTEN = "127.0.0.1:9400"


def parse_role(val: str | None) -> str:
    """``TPU_SERVING_ROLE`` -> role. Unknown values raise: a typo'd
    role silently serving fused would be a silently mis-deployed pool,
    the one misconfiguration class that must fail at startup."""
    role = (val or ROLE_FUSED).strip().lower()
    if role not in ROLES:
        raise ValueError(f"TPU_SERVING_ROLE={val!r}: expected one of "
                         f"{ROLES}")
    return role


def _parse_addr(spec: str, what: str) -> tuple[str, int]:
    host, _, port = spec.strip().rpartition(":")
    if not host or not port.isdigit():
        raise ValueError(f"{what}={spec!r}: expected host:port")
    return host, int(port)


def wire_role(engine, role: str, cfg, *, logger=None, metrics=None):
    """Attach the role's PD half to a built engine: a decode worker
    grows the KV-ingest listener (``TPU_PD_LISTEN``); a prefill worker
    grows the coordinator against ``TPU_PD_PEER``. Both sides derive
    the handshake identity from the SAME model fingerprint the Redis
    tier namespaces by — two pools serving different weights refuse
    each other at hello instead of exchanging wrong attention state."""
    gen = engine.generator
    if gen is None:
        raise ValueError(f"TPU_SERVING_ROLE={role}: requires a decoder "
                         "model (TPU_MODEL llama family)")
    from ..tpu.kvcache import model_fingerprint

    fingerprint = model_fingerprint(gen.cfg, gen.params, extra="pd")
    window = max(1, cfg.get_int("TPU_PD_WINDOW_MB", 8)) << 20
    if role == ROLE_DECODE:
        if gen.mesh is not None:
            # same startup-loud contract as the prefill role: a sharded
            # decode worker would handshake fine and then 500 every
            # KV_EOF at _validate_ingest — fail the deploy, not the
            # requests. Names the exact config rows in conflict: mesh
            # SERVING itself is supported (TPU_SHARDING alone is fine,
            # paged included) — it is the ROLE pairing that is refused
            # until ingest learns to install shard-split rows (the
            # role x engine-kind matrix in docs/advanced-guide/
            # disaggregated-serving.md).
            raise ValueError(
                "TPU_SERVING_ROLE=decode cannot run with "
                f"TPU_SHARDING={cfg.get('TPU_SHARDING')!r}: shipped-KV "
                "ingest installs dense rows and is not yet shard-aware "
                "(mesh decode stays refused until it is). Unset "
                "TPU_SHARDING on the decode pool, or drop "
                "TPU_SERVING_ROLE to serve this mesh fused")
        host, port = _parse_addr(
            cfg.get_or_default("TPU_PD_LISTEN", DEFAULT_LISTEN),
            "TPU_PD_LISTEN")
        engine.pd_ingest = KVIngestServer(
            gen, fingerprint, host, port, logger=logger, metrics=metrics,
            window_bytes=window)
        engine.serving_role = ROLE_DECODE
        if logger is not None:
            logger.info({"event": "pd decode role wired",
                         "listen": f"{host}:{engine.pd_ingest.port}"})
        return engine.pd_ingest
    if role == ROLE_PREFILL:
        if gen.mesh is not None:
            raise ValueError(
                "TPU_SERVING_ROLE=prefill cannot run with "
                f"TPU_SHARDING={cfg.get('TPU_SHARDING')!r}: the KV-ship "
                "wire format is dense single-device rows, and a mesh "
                "row would ship per-shard frames no decode pool "
                "ingests yet (see the role x engine-kind matrix in "
                "docs/advanced-guide/disaggregated-serving.md). Unset "
                "TPU_SHARDING on the prefill pool, or drop "
                "TPU_SERVING_ROLE to serve this mesh fused")
        if getattr(gen, "_paged", False):
            raise ValueError("TPU_SERVING_ROLE=prefill requires a "
                             "contiguous engine (set TPU_PAGED_BLOCKS=0 "
                             "on the prefill pool; the DECODE pool may "
                             "be paged)")
        peer = cfg.get("TPU_PD_PEER")
        if not peer:
            raise ValueError("TPU_SERVING_ROLE=prefill requires "
                             "TPU_PD_PEER=host:port (the decode "
                             "worker's TPU_PD_LISTEN address)")
        host, port = _parse_addr(peer, "TPU_PD_PEER")
        engine.pd_prefill = PDPrefill(
            gen, fingerprint, host, port, logger=logger, metrics=metrics,
            ship_block=max(1, cfg.get_int("TPU_PD_BLOCK", 16)),
            window_bytes=window,
            # durable streams: a decode-peer death mid-stream re-hands
            # the relay off as a continuation instead of shedding it
            resume=cfg.get_bool("TPU_RESUME_PD", True),
            resume_max=cfg.get_int("TPU_RESUME_MAX", 3),
            resume_wait_s=cfg.get_float("TPU_RESUME_WAIT_S", 5.0))
        engine.serving_role = ROLE_PREFILL
        if logger is not None:
            logger.info({"event": "pd prefill role wired",
                         "peer": f"{host}:{port}"})
        return engine.pd_prefill
    engine.serving_role = ROLE_FUSED
    return None
