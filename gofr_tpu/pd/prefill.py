"""Prefill-side coordinator: the client half of disaggregated serving.

A prefill worker (``TPU_SERVING_ROLE=prefill``) runs ONLY prefill
compute: each ``generate`` call admits through the local engine's
normal gate/deadline/SLO machinery in KV-only mode (the chunk lattice
runs, the first token samples, no decode slot is held past the
prefill), ships the slot's KV to the decode peer as checksummed int8
block frames — streamed per ship block as prefill chunks complete, so
the peer's host-side assembly overlaps this worker's compute — and
relays the decode worker's token stream back to the client through a
``RelayStream`` (a ``PushStream``: the transports' zero-handoff sink
protocol works unchanged).

The failure contract mirrors the gate's shed discipline: a down or
mid-stream-lost decode peer surfaces as ``DecodePeerUnavailable``
(503 + Retry-After) — a SHED, not a failure — while this worker keeps
serving prefills and the reconnect backoff re-arms the path; decode-
side sheds (429), deadline expiries (504) and transfer faults (502)
arrive typed through the ERR relay and re-raise as themselves.
"""

from __future__ import annotations

import itertools
import json
import random
import socket
import threading
import time

from ..errors import ConnectionLost, DeadlineExceeded, GofrError
from ..resilience import current_deadline, current_slo_class
from ..service.reconnect import ReconnectBackoff
from ..tpu.kvcache.quant import concat_blocks, encode_block
from ..wire import PushStream, observe_backlog
from . import protocol as p

_BACKOFF_S = 0.5
_BACKOFF_CAP_S = 15.0


class RelayStream(PushStream):
    """The client-facing stream of a P/D-split request: tokens pushed
    by the peer reader thread (or straight into a transport sink),
    terminals follow GenStream's convention (error then None). Carries
    the attribute surface transports read off GenStream (``trace``,
    ``prompt_len``, ``request_id``, ``cancel``, and the durable-stream
    fields ``seed`` / ``cursor_base`` / ``cache_tokens``).

    The stream OUTLIVES any one wire request: a decode-peer loss
    re-submits the same RelayStream under a fresh ``_wire_id`` (the
    re-handoff), so the client keeps reading one queue while the
    request changes wire identity underneath."""

    def __init__(self, request_id: int, owner: "PDPrefill",
                 logprobs: bool = False):
        super().__init__()
        self.request_id = request_id
        self._wire_id = request_id  # current wire req_id (re-handoffs bump)
        self.logprobs = logprobs
        self.prompt_len = 0
        self.trace: dict[str, float] = {}
        self.cancelled = threading.Event()
        self.failed: str | None = None
        self.seed: int | None = None
        self.cursor_base = 0       # client-replayed tokens before this stream
        self.cache_tokens = 0      # copied from the local prefill's stream
        self.emitted: list[int] = []  # tokens THIS stream delivered
        self.resumes = 0
        self.resume_info: dict | None = None  # everything a re-submit needs
        self._owner = owner
        self._local = None  # the prefill-side GenStream while it runs
        self._done = False

    def tokens(self) -> list[int]:
        return [t[0] if isinstance(t, tuple) else t for t in self]

    def cancel(self) -> None:
        self.cancelled.set()
        local = self._local
        if local is not None:
            local.cancel()
        self._owner._cancel(self._wire_id)


class _Shipper:
    """Accumulates the generator's KV-sink ranges and emits checksummed
    block frames (``quant.encode_block`` — the Redis tier's codec) in
    token order through the connection's windowed send path. Raises out
    of the sink on ship failure; the generator converts that into a
    per-request failure, never loop recovery."""

    def __init__(self, conn: p.Conn, req_id: int, block: int,
                 deadline=None, metrics=None):
        self.conn = conn
        self.req_id = req_id
        self.block = max(1, int(block))
        self.deadline = deadline
        self.metrics = metrics
        self.parts: list = []
        self.buffered = 0
        self.sent = 0
        self.frames = 0
        self.t_first: float | None = None  # first frame on the wire
        self.error: BaseException | None = None

    def _window_deadline(self) -> float:
        if self.deadline is not None:
            return max(0.05, min(30.0, self.deadline.remaining()))
        return 30.0

    def _emit(self, kv) -> None:
        if self.t_first is None:
            self.t_first = time.monotonic()
        frame = encode_block(kv)
        self.conn.send_windowed(p.pack_kv(self.req_id, self.sent, frame),
                                deadline_s=self._window_deadline())
        self.sent += kv.plen
        self.frames += 1
        observe_backlog(self.metrics, self.conn.pending_bytes(),
                        role="pd-prefill")
        if self.metrics is not None:
            try:
                self.metrics.increment_counter("app_tpu_pd_kv_frames_total",
                                               direction="out")
            except Exception:
                pass

    def ship(self, kv, start: int, total: int) -> None:
        """The generator's kv_sink: one host KV slab covering prompt
        positions [start, start+kv.plen) — called per prefill chunk,
        in order. Frames cut at ship-block boundaries; the trailing
        partial flushes in finish()."""
        try:
            if start != self.sent + self.buffered:
                raise p.KVTransferError(
                    f"kv ship discontinuity: range starts at {start}, "
                    f"expected {self.sent + self.buffered}")
            self.parts.append(kv)
            self.buffered += kv.plen
            if self.buffered < self.block:
                return
            merged = (self.parts[0] if len(self.parts) == 1
                      else concat_blocks(self.parts))
            off = 0
            while self.buffered - off >= self.block:
                self._emit(merged.slice_tokens(off, off + self.block))
                off += self.block
            self.parts = [merged.slice_tokens(off, self.buffered)] \
                if self.buffered > off else []
            self.buffered -= off
        except BaseException as e:
            self.error = e
            raise

    def finish(self) -> None:
        try:
            if self.parts:
                merged = (self.parts[0] if len(self.parts) == 1
                          else concat_blocks(self.parts))
                self._emit(merged)
                self.parts = []
                self.buffered = 0
        except BaseException as e:
            self.error = e
            raise
        # the wire segment of the critical path: first frame enqueue to
        # the final windowed send returning (histogram face of the
        # timeline's ship window)
        if self.metrics is not None and self.t_first is not None:
            try:
                self.metrics.record_histogram(
                    "app_tpu_pd_ship_duration",
                    time.monotonic() - self.t_first)
            except Exception:
                pass


class PDPrefill:
    """Coordinates KV-only prefill + ship + token relay against one
    decode peer. Thread model: ``generate`` runs on transport handler
    threads; the KV sink runs on the serving loop thread; one reader
    thread per connection dispatches TOK/END/ERR to RelayStreams; one
    finisher thread per request observes the local prefill's outcome
    and sends KV_EOF."""

    def __init__(self, generator, fingerprint: str, peer_host: str,
                 peer_port: int, *, logger=None, metrics=None,
                 ship_block: int = 16, window_bytes: int = 8 << 20,
                 connect_timeout_s: float = 3.0, resume: bool = True,
                 resume_max: int = 3, resume_wait_s: float = 5.0):
        self.gen = generator
        self.fingerprint = fingerprint
        self.peer = (peer_host, int(peer_port))
        self.logger = logger
        self.metrics = metrics
        self.ship_block = int(ship_block)
        self.window_bytes = int(window_bytes)
        self.connect_timeout_s = float(connect_timeout_s)
        self.resume = bool(resume)
        self.resume_max = max(0, int(resume_max))
        self.resume_wait_s = float(resume_wait_s)
        import numpy as np

        from ..tpu.kvcache import KVLayout

        cache = generator.cache
        self.layout = KVLayout(
            generator.cfg.n_layers, generator.cfg.n_kv_heads,
            generator.cfg.head_dim, cache.k_scale is not None,
            np.dtype(str(cache.k.dtype)), generator.max_seq)
        self._hello = p.hello_payload(fingerprint, self.layout)
        self._ids = itertools.count(1)
        self._conn: p.Conn | None = None
        self._conn_lock = threading.Lock()
        self._streams: dict[int, RelayStream] = {}
        self._streams_lock = threading.Lock()
        # one reconnect convention (service/reconnect.py): shared by
        # the connect path here and the reader-thread loss path
        self._reconnect = ReconnectBackoff(_BACKOFF_S, _BACKOFF_CAP_S)
        self._closed = False
        self._peer_debug_url: str | None = None  # learned from HELLO_OK
        self.relayed = 0
        self.reconnects = 0
        self.peer_losses = 0
        self.resumed = 0

    def _note_peer_clock(self, t0, t1, t2, t3, debug_port=None) -> None:
        """Feed one NTP sample for the decode peer into the Observe
        bundle's clock registry (observe/clock.py) — the handshake and
        every REQ->END round trip are free carriers. No-op without an
        Observe bundle; never raises into the serving path."""
        clock = getattr(getattr(self.gen, "_observe", None), "clock", None)
        if clock is None:
            return
        try:
            name = f"pd:{self.peer[0]}:{self.peer[1]}"
            if debug_port:
                self._peer_debug_url = \
                    f"http://{self.peer[0]}:{int(debug_port)}"
            if t0 is None or t1 is None or t2 is None:
                clock.note_peer(name, debug_url=self._peer_debug_url)
            else:
                clock.observe(name, float(t0), float(t1), float(t2),
                              float(t3), debug_url=self._peer_debug_url)
        except Exception:
            pass  # telemetry must never take the serving path down

    # -- connection management ----------------------------------------------
    @property
    def connected(self) -> bool:
        return self._conn is not None

    def _ensure_conn(self) -> p.Conn:
        conn = self._conn
        if conn is not None and not conn.closed:
            return conn
        if self._closed:
            raise p.DecodePeerUnavailable("pd prefill coordinator closed")
        blocked = self._reconnect.blocked()
        if blocked > 0:
            raise p.DecodePeerUnavailable(
                f"decode peer {self.peer[0]}:{self.peer[1]} in reconnect "
                "backoff", retry_after=blocked)
        with self._conn_lock:
            conn = self._conn
            if conn is not None and not conn.closed:
                return conn
            sock = None
            conn = None
            try:
                sock = socket.create_connection(
                    self.peer, timeout=self.connect_timeout_s)
                # the handshake stays under the SAME timeout: a peer
                # that accepts but never answers hello (stopped
                # process, wrong service) must not wedge this
                # generate() — and everyone behind _conn_lock — forever
                sock.settimeout(self.connect_timeout_s)
                conn = p.Conn(sock, window_bytes=self.window_bytes)
                t0 = time.time()
                conn.send(p.pack_json(p.HELLO, 0, self._hello), block=True)
                msg = p.read_msg(sock)
                t3 = time.time()
                if msg is None:
                    raise ConnectionLost("peer closed during hello")
                mtype, _, payload = msg
                if mtype == p.ERR:
                    err = p.error_from_wire(json.loads(bytes(payload)))
                    raise GofrError(f"decode peer refused hello: {err}")
                if mtype != p.HELLO_OK:
                    raise GofrError("unexpected hello reply")
                sock.settimeout(None)
                try:
                    reply = json.loads(bytes(payload)) if payload else {}
                except ValueError:
                    reply = {}  # pre-clock peer: HELLO_OK alone is fine
                # clock piggyback: the handshake IS an NTP exchange when
                # the peer stamped its receive/send times into HELLO_OK
                self._note_peer_clock(t0, reply.get("clock_t1"),
                                      reply.get("clock_t2"), t3,
                                      debug_port=reply.get("debug_port"))
            except GofrError:
                # a REFUSED hello is a configuration error (wrong model/
                # weights behind the address): no silent retry loop —
                # surface it and back off long. Close what we opened:
                # every failed attempt must cost zero fds.
                self._close_handshake(conn, sock)
                self._reconnect.hold()
                raise
            except Exception as e:  # noqa: BLE001 — down peer = shed
                self._close_handshake(conn, sock)
                retry = self._reconnect.failure()
                raise p.DecodePeerUnavailable(
                    f"decode peer {self.peer[0]}:{self.peer[1]} "
                    f"unreachable: {e!r}", retry_after=retry) from e
            self._reconnect.success()
            self._conn = conn
            self.reconnects += 1
            threading.Thread(target=self._read_loop, args=(conn,),
                             name="gofr-pd-relay", daemon=True).start()
            if self.logger is not None:
                self.logger.info({"event": "pd decode peer connected",
                                  "peer": f"{self.peer[0]}:{self.peer[1]}"})
            return conn

    @staticmethod
    def _close_handshake(conn, sock) -> None:
        try:
            if conn is not None:
                conn.close()
            elif sock is not None:
                sock.close()
        except OSError:
            pass

    def _read_loop(self, conn: p.Conn) -> None:
        while True:
            msg = p.read_msg(conn.sock)
            if msg is None:
                break
            mtype, req_id, payload = msg
            with self._streams_lock:
                rs = self._streams.get(req_id)
            if rs is None:
                continue
            if mtype == p.TOK:
                tok, cursor, lp = p.unpack_tok(payload)
                # the resume contract's splice check: a token the
                # client already has (a re-handoff over-replaying)
                # is swallowed, never double-delivered
                if cursor < rs.cursor_base + len(rs.emitted):
                    continue
                if not rs.trace.get("first_put"):
                    rs.trace["first_put"] = time.monotonic()
                rs.emitted.append(int(tok))
                rs._push((tok, lp) if rs.logprobs else tok)
            elif mtype == p.END:
                t3 = time.time()
                try:
                    endp = json.loads(bytes(payload)) if payload else {}
                except ValueError:
                    endp = {}
                # per-request clock sample: REQ carried sent_wall, END
                # echoes it with the peer's receive/send stamps — the
                # NTP hold-time term (t2-t1) subtracts the whole decode,
                # so a busy pair converges one sample per request
                if endp.get("req_recv_wall") is not None:
                    self._note_peer_clock(
                        endp.get("req_sent_wall"),
                        endp.get("req_recv_wall"),
                        endp.get("end_sent_wall"), t3)
                if endp.get("breakdown"):
                    # the decode worker's segment view of this request,
                    # surfaced beside the local trace for /debug pages
                    rs.trace["peer_breakdown"] = endp["breakdown"]
                with self._streams_lock:
                    self._streams.pop(req_id, None)
                rs._done = True
                rs._push(None)
            elif mtype == p.ERR:
                err = p.error_from_wire(json.loads(bytes(payload)))
                with self._streams_lock:
                    self._streams.pop(req_id, None)
                rs.failed = str(err)
                rs._done = True
                rs._q.put(err)
                rs._q.put(None)
        self._on_conn_lost(conn)

    def _fail_stream(self, rs: RelayStream, err: BaseException) -> None:
        if rs._done:
            return
        rs.failed = str(err)
        rs._done = True
        rs._q.put(err)
        rs._q.put(None)

    def _on_conn_lost(self, conn: p.Conn) -> None:
        """The decode peer vanished (crash, kill, network). Relays with
        >= 1 delivered token RESUME (durable streams): a bounded waiter
        re-handshakes the peer — its restart, or a replacement behind
        the same address — and re-submits prompt+emitted as a
        continuation; the client's stream splices token-exact and never
        sees the loss. Relays with NOTHING delivered are SHED typed
        (503 + Retry-After) as before: the gateway's pre-commit
        failover owns those. The path enters reconnect backoff either
        way; this worker's engine is untouched."""
        with self._conn_lock:
            if self._conn is conn:
                self._conn = None
                self._reconnect.failure()
        conn.close()
        with self._streams_lock:
            orphans = list(self._streams.items())
            self._streams.clear()
        if orphans:
            self.peer_losses += 1
            if self.logger is not None:
                self.logger.warn({"event": "pd decode peer lost",
                                  "in_flight": len(orphans)})
        shed: list[RelayStream] = []
        for req_id, rs in orphans:
            if (self.resume and rs.emitted and not rs._done
                    and not rs.cancelled.is_set()
                    and rs.resumes < self.resume_max
                    and rs.resume_info is not None):
                rs.resumes += 1
                threading.Thread(target=self._resume_relay, args=(rs,),
                                 name=f"gofr-pd-resume-{req_id}",
                                 daemon=True).start()
            else:
                shed.append(rs)
        err = p.DecodePeerUnavailable(
            "decode peer lost mid-stream",
            retry_after=self._reconnect.retry_after())
        for rs in shed:
            self._fail_stream(rs, err)
        if self.metrics is not None and orphans:
            try:
                self.metrics.increment_counter(
                    "app_tpu_pd_peer_losses_total")
            except Exception:
                pass

    def _resume_relay(self, rs: RelayStream) -> None:
        """The re-handoff waiter: retry the handshake (bounded by
        ``TPU_RESUME_WAIT_S`` and the request deadline — a restarting
        decode worker needs a moment to bind) and re-submit the SAME
        RelayStream as a continuation under a fresh wire req_id.
        Exhaustion falls back to the legacy typed shed; the typed
        line's resume token still lets the CLIENT continue."""
        info = rs.resume_info or {}
        deadline = info.get("deadline")
        t_end = time.monotonic() + self.resume_wait_s
        while not rs.cancelled.is_set() and not rs._done:
            if deadline is not None and deadline.remaining() <= 0:
                self._fail_stream(rs, DeadlineExceeded(
                    "deadline expired while resuming after decode "
                    "peer loss"))
                return
            try:
                emitted = list(info.get("emitted0") or []) \
                    + list(rs.emitted)
                self._submit(rs, emitted)
            except p.DecodePeerUnavailable as e:
                if time.monotonic() < t_end:
                    time.sleep(min(0.25, self.resume_wait_s))
                    continue
                self._fail_stream(rs, e)
                return
            except BaseException as e:  # noqa: BLE001 — typed fallback
                self._fail_stream(rs, e)
                return
            self.resumed += 1
            if self.metrics is not None:
                try:
                    self.metrics.increment_counter(
                        "app_tpu_pd_resumes_total")
                except Exception:
                    pass
            if self.logger is not None:
                self.logger.info({"event": "pd stream resumed",
                                  "emitted": len(emitted),
                                  "attempt": rs.resumes})
            return

    def _cancel(self, req_id: int) -> None:
        with self._streams_lock:
            self._streams.pop(req_id, None)
        conn = self._conn
        if conn is not None and not conn.closed:
            try:
                conn.send(p.pack_msg(p.CANCEL, req_id), block=True)
            except Exception:
                pass

    # -- the serving path ----------------------------------------------------
    def generate(self, prompt, max_new_tokens: int = 128,
                 temperature: float = 0.0, top_k: int = 0,
                 eos_id=None, adapter: int = 0, logprobs: bool = False,
                 deadline=None, slo_class: str | None = None,
                 seed: int | None = None,
                 continue_from=None) -> RelayStream:
        """The prefill worker's ``generate``: same signature and same
        ambient deadline/SLO pickup as the fused engine's, returning a
        RelayStream of the decode peer's tokens. ``seed`` /
        ``continue_from`` follow the generator's durable-streams
        contract; a sampled request's seed is pinned HERE and crosses
        the wire in REQ, so a decode-peer re-handoff — and a
        client-side resume — redraw the exact same sample stream."""
        if deadline is None:
            deadline = current_deadline()
        if slo_class is None:
            slo_class = current_slo_class()
        import numpy as np

        emitted0: list[int] = []
        if continue_from is not None:
            base, em = continue_from
            prompt = np.asarray(base, np.int32).reshape(-1)
            emitted0 = [int(t) for t in em]
        else:
            prompt = np.asarray(prompt, np.int32).reshape(-1)
        if temperature > 0 and seed is None:
            seed = random.getrandbits(31)
        traceparent = None
        from .. import tracing

        span = tracing.current_span()
        if span is not None:
            traceparent = span.traceparent()
        if isinstance(eos_id, (set, frozenset, list, tuple)):
            eos_wire: object = sorted(int(t) for t in eos_id)
        else:
            eos_wire = int(eos_id) if eos_id is not None else None
        rs = RelayStream(0, self, logprobs=logprobs)
        rs.prompt_len = int(len(prompt)) + len(emitted0)
        rs.cursor_base = len(emitted0)
        rs.seed = seed
        rs.trace["submit"] = time.monotonic()
        rs.resume_info = {
            "prompt": prompt, "emitted0": emitted0,
            "max_new": int(max_new_tokens),
            "temperature": float(temperature), "top_k": int(top_k),
            "eos_id": eos_id, "eos_wire": eos_wire,
            "adapter": int(adapter), "slo_class": slo_class,
            "deadline": deadline, "traceparent": traceparent,
            "seed": seed}
        self._submit(rs, emitted0)
        self.relayed += 1
        if self.metrics is not None:
            try:
                self.metrics.increment_counter("app_tpu_pd_requests_total",
                                               role="prefill")
            except Exception:
                pass
        return rs

    def _submit(self, rs: RelayStream, emitted: list) -> None:
        """Submit — or RE-submit after a decode-peer loss — one relay
        under a fresh wire req_id. The local KV-only prefill admits
        prompt+emitted as a continuation when tokens were already
        delivered: a warm re-handoff recomputes only the un-cached
        tail, and the shipped KV covers the whole concat (the decode
        side's plen check holds)."""
        info = rs.resume_info or {}
        conn = self._ensure_conn()
        req_id = next(self._ids)
        rs._wire_id = req_id
        if not rs.request_id:
            rs.request_id = req_id
        prompt = info["prompt"]
        deadline = info["deadline"]
        meta = {"prompt": prompt.tolist(),
                "plen": int(len(prompt)) + len(emitted),
                "max_new": info["max_new"],
                "temperature": info["temperature"],
                "top_k": info["top_k"], "eos": info["eos_wire"],
                "adapter": info["adapter"],
                "slo_class": info["slo_class"],
                "deadline_s": (round(deadline.remaining(), 6)
                               if deadline is not None else None),
                "traceparent": info["traceparent"],
                "seed": info["seed"],
                # hop stamp: echoed back in END so every relayed request
                # doubles as a clock sample (observe/clock.py)
                "sent_wall": time.time()}
        if emitted:
            meta["resume_emitted"] = [int(t) for t in emitted]
        with self._streams_lock:
            self._streams[req_id] = rs
        shipper = _Shipper(conn, req_id, self.ship_block,
                           deadline=deadline, metrics=self.metrics)
        try:
            # REQ leaves BEFORE the local submit: the serving loop may
            # admit and ship the first KV frame before this thread runs
            # again, and the peer must already know the request
            conn.send(p.pack_json(p.REQ, req_id, meta), block=True)
            local = self.gen.generate(
                prompt, max_new_tokens=info["max_new"],
                temperature=info["temperature"], top_k=info["top_k"],
                eos_id=info["eos_id"], adapter=info["adapter"],
                logprobs=True, deadline=deadline,
                slo_class=info["slo_class"], kv_sink=shipper.ship,
                seed=info["seed"],
                continue_from=((prompt, emitted) if emitted else None))
        except (EOFError, OSError) as e:
            # the peer died under the REQ send: a SHED, not a 500 —
            # the typed-503 contract holds at every loss site
            self._cancel(req_id)
            raise p.DecodePeerUnavailable(
                f"decode peer lost during submit: {e!r}",
                retry_after=self._reconnect.retry_after()) from e
        except BaseException:
            self._cancel(req_id)
            raise
        rs._local = local
        threading.Thread(target=self._finish, args=(conn, req_id, rs,
                                                    local, shipper),
                         name=f"gofr-pd-finish-{req_id}",
                         daemon=True).start()

    def _finish(self, conn: p.Conn, req_id: int, rs: RelayStream,
                local, shipper: _Shipper) -> None:
        """Wait out the local KV-only prefill (its single delivered
        token IS the first token), flush the trailing partial frame,
        then hand the stream off with KV_EOF. A local failure (shed,
        deadline, ship fault, device recovery) cancels the peer's
        assembly and fails the relay with the TYPED local error."""
        try:
            toks = list(local)  # [ (first_token, first_lp) ] or raises
            if not toks:
                raise GofrError("kv-only prefill delivered no first token")
            first, first_lp = toks[0]
            shipper.finish()
            rs.trace["prefill_done"] = time.monotonic()
            # durable-stream surface: how warm THIS prefill ran (the
            # resume contract's recompute report) and the engine's
            # pinned auto-seed, for resume tokens
            rs.cache_tokens = int(getattr(local, "cache_tokens", 0) or 0)
            if getattr(local, "seed", None) is not None:
                rs.seed = int(local.seed)
            # FIRST TOKEN LEAVES HERE, from the prefill pool: TTFT is
            # the prefill worker's latency alone — no handoff, no
            # decode-slot wait on its critical path (the decode worker
            # knows not to re-relay it; tokens 2+ are its stream). The
            # push precedes KV_EOF, so wire tokens can only follow it.
            if not rs._done:
                rs.trace.setdefault("first_put", time.monotonic())
                rs.emitted.append(int(first))
                rs._push((int(first), float(first_lp)) if rs.logprobs
                         else int(first))
            conn.send(p.pack_json(p.KV_EOF, req_id, {
                "first_token": int(first), "first_lp": float(first_lp),
                # THIS submit's prefill length (a re-handoff's concat
                # is longer than the original rs.prompt_len)
                "plen": int(getattr(local, "prompt_len", rs.prompt_len)),
                "blocks": shipper.frames}),
                block=True)
        except BaseException as e:  # noqa: BLE001 — typed per-request fail
            err: BaseException = shipper.error or e
            if isinstance(err, (EOFError, OSError)):
                err = p.DecodePeerUnavailable(
                    "decode peer lost during kv ship",
                    retry_after=self._reconnect.retry_after())
            self._cancel(req_id)
            # a re-handoff may have re-submitted this stream under a
            # NEW wire id while this (old) finisher was dying on the
            # old connection — never fail a stream someone else owns
            if not rs._done and rs._wire_id == req_id:
                rs.failed = str(err)
                rs._done = True
                rs._q.put(err)
                rs._q.put(None)

    def stats(self) -> dict:
        with self._streams_lock:
            in_flight = len(self._streams)
        return {"peer": f"{self.peer[0]}:{self.peer[1]}",
                "connected": self.connected, "in_flight": in_flight,
                "relayed": self.relayed, "reconnects": self.reconnects,
                "peer_losses": self.peer_losses,
                "resumed": self.resumed,
                "ship_block": self.ship_block,
                "window_bytes": self.window_bytes}

    def close(self) -> None:
        self._closed = True
        conn = self._conn
        if conn is not None:
            conn.close()
        with self._streams_lock:
            orphans = list(self._streams.values())
            self._streams.clear()
        for rs in orphans:
            if not rs._done:
                rs._q.put(GofrError("pd prefill coordinator closed"))
                rs._q.put(None)
