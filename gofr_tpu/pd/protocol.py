"""The KV-ship wire protocol between prefill and decode workers.

One persistent TCP connection per (prefill worker, decode worker) pair,
multiplexing any number of in-flight requests by ``req_id``. Every
message is one length-prefixed frame::

    [u32 length][u8 type][u32 req_id][payload]

``length`` covers everything after itself (type + req_id + payload).
Payloads are JSON except KV frames, whose payload is::

    [u32 start][kvcache.quant.encode_block frame]

— the SAME checksummed int8 block frame the Redis tier stores, reused
verbatim as the transfer codec: the decode side validates every frame
(magic, version, shape-vs-layout, sha256 digest) with
``quant.decode_block`` before any byte goes near a pool row, so a
truncated or corrupted frame is a typed per-request failure, never a
poisoned cache row and never a dead ingest loop.

Flow (prefill -> decode unless noted)::

    HELLO {fingerprint, layers, kv_heads, head_dim, version}
    <- HELLO_OK {}                      (or ERR req_id=0: refuse + close)
    REQ  {prompt, max_new, temperature, top_k, eos, adapter,
          slo_class, deadline_s, traceparent, plen, seed,
          resume_emitted?}              (resume_emitted marks a durable-
                                         stream re-handoff: the decode
                                         side admits prompt+emitted as a
                                         ``continue_from`` continuation)
    KV   [start][frame] ...             (streamed per ship block, in
                                         token order, as prefill chunks
                                         complete — ingest assembly
                                         overlaps prefill compute and
                                         wire transfer)
    KV_EOF {first_token, first_lp, plen, blocks}
    <- TOK [i32 token][i32 cursor][f32 lp] ...
                                        (decode -> prefill, per token;
                                         cursor = absolute generated-
                                         token index of the ORIGINAL
                                         request — the stream resume
                                         contract's monotone cursor,
                                         so a re-handoff splices
                                         token-exact)
    <- END {tokens}                     (or <- ERR {code, message,
                                         retry_after})
    CANCEL {}                           (prefill -> decode, either
                                         direction of giving up)

Writes ride the ``wire.py`` fast path: frames append to an ``Outbox``
drained into a vectored ``SocketWriter`` (token bursts coalesce into
one ``sendmsg``), and the ship window (``TPU_PD_WINDOW_MB``) bounds
outbox + writer backlog — a KV send past the window blocks the
producer until the peer drains, which is the honest flow control the
backlog alone would hide.
"""

from __future__ import annotations

import json
import socket
import struct
import threading
import time

from ..errors import (ConnectionLost, DeadlineExceeded, HTTPError,
                      ServiceUnavailable, TooManyRequests,
                      format_retry_after)
from ..wire import Outbox, SocketWriter

PD_VERSION = 2  # v2: TOK carries the resume cursor; REQ carries
#                 seed / resume_emitted (durable streams, PR 18)

# message types
HELLO = 0
HELLO_OK = 1
REQ = 2
KV = 3
KV_EOF = 4
TOK = 5
END = 6
ERR = 7
CANCEL = 8

_HEAD = struct.Struct("<IBI")   # length, type, req_id
_KV_START = struct.Struct("<I")
_TOK = struct.Struct("<iif")    # token id, cursor, logprob (f32: wire
#                                 precision)

# one message may carry at most this much (a KV frame for one ship
# block of a 70B-class model is ~MBs; anything past this is a framing
# error, not a legitimate payload)
MAX_MSG = 256 << 20


class KVTransferError(HTTPError):
    """A KV frame failed validation at the transfer boundary (bad
    checksum, truncated payload, layout mismatch) or the stream was cut
    mid-transfer. Fails the ONE request it belongs to — 502 on HTTP,
    INTERNAL on gRPC — and never touches device state."""

    status_code = 502


class DecodePeerUnavailable(ServiceUnavailable):
    """The decode pool peer is down/unreachable: the request is SHED
    with a Retry-After (the prefill worker keeps serving and the
    reconnect loop re-arms the path), the 503 sibling of the gate's
    429 — clients retry exactly like any other shed."""

    def __init__(self, message: str = "decode peer unavailable",
                 retry_after: float = 1.0):
        super().__init__(message)
        self.retry_after = retry_after
        self.headers = {"Retry-After": format_retry_after(retry_after)}


def error_to_wire(e: BaseException) -> dict:
    """Exception -> ERR payload. The http status code IS the type on
    the wire; retry_after survives so the prefill side re-raises a
    shed that still advises honest backoff."""
    code = getattr(e, "status_code", 500)
    return {"code": int(code), "message": str(e)[:500],
            "retry_after": getattr(e, "retry_after", None)}


def error_from_wire(p: dict) -> BaseException:
    """ERR payload -> the typed exception the prefill worker delivers
    into the client's stream: sheds stay sheds (429 + Retry-After),
    deadline stays 504, transfer faults stay 502 — the process
    boundary never flattens the error contract to a bare 500."""
    code = int(p.get("code", 500))
    msg = p.get("message", "decode worker error")
    retry_after = p.get("retry_after")
    if code == 429:
        return TooManyRequests(msg, retry_after=retry_after)
    if code == 504:
        return DeadlineExceeded(msg)
    if code == 502:
        return KVTransferError(msg)
    if code == 503:
        return DecodePeerUnavailable(msg, retry_after=retry_after or 1.0)
    return HTTPError(msg, status_code=code)


def pack_msg(mtype: int, req_id: int, payload: bytes = b"") -> bytes:
    return _HEAD.pack(5 + len(payload), mtype, req_id) + payload


def pack_json(mtype: int, req_id: int, obj: dict) -> bytes:
    return pack_msg(mtype, req_id, json.dumps(obj).encode())


def pack_kv(req_id: int, start: int, frame: bytes) -> bytes:
    return pack_msg(KV, req_id, _KV_START.pack(start) + frame)


def pack_tok(req_id: int, token: int, cursor: int,
             lp: float | None) -> bytes:
    return pack_msg(TOK, req_id, _TOK.pack(
        int(token), int(cursor), 0.0 if lp is None else float(lp)))


def unpack_tok(payload) -> tuple[int, int, float]:
    return _TOK.unpack(bytes(payload[:_TOK.size]))


def unpack_kv(payload) -> tuple[int, bytes]:
    (start,) = _KV_START.unpack(bytes(payload[:_KV_START.size]))
    return start, bytes(payload[_KV_START.size:])


def _read_exact(sock: socket.socket, n: int) -> bytes | None:
    buf = bytearray()
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except OSError:
            return None
        if not chunk:
            return None
        buf += chunk
    return bytes(buf)


def read_msg(sock: socket.socket) -> tuple[int, int, bytes] | None:
    """One framed message off the socket, or None on EOF/close. A
    length past MAX_MSG is treated as EOF (protocol desync: nothing
    after it can be trusted, the connection is torn down)."""
    head = _read_exact(sock, 4)
    if head is None:
        return None
    (length,) = struct.unpack("<I", head)
    if length < 5 or length > MAX_MSG:
        return None
    body = _read_exact(sock, length)
    if body is None:
        return None
    mtype, req_id = struct.unpack_from("<BI", body)
    return mtype, req_id, body[5:]


class Conn:
    """One PD connection's send half: an ``Outbox`` (ordered,
    thread-combining — token bursts from the serving loop coalesce)
    draining into a vectored ``SocketWriter``. ``send`` is the
    nonblocking fast path (stalls park in the writer backlog and ride
    out with the next frame); ``send_windowed`` is the KV-ship path —
    it blocks once ``pending_bytes`` crosses the ship window, which is
    the backpressure contract: a slow decode peer slows the prefill
    worker's ship loop instead of ballooning its memory."""

    def __init__(self, sock: socket.socket, window_bytes: int = 8 << 20):
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        self.sock = sock
        self.writer = SocketWriter(sock)
        self.window = int(window_bytes)
        self._pending = 0
        self._plock = threading.Lock()
        self.outbox = Outbox(self._drain)
        self.closed = False
        self.bytes_sent = 0
        self.kv_frames = 0

    def _drain(self, batch, block: bool) -> int:
        n = sum(len(b) for b in batch)
        try:
            self.writer.write(batch, block=block)
        except OSError as e:
            # a dying socket (BrokenPipe/ConnectionReset/...) is ONE
            # failure class for every caller: EOFError, with the conn
            # marked closed — the prefill side maps it to the typed
            # 503 shed instead of leaking a raw OSError to the client
            self.closed = True
            raise ConnectionLost(f"pd connection lost: {e!r}") from e
        finally:
            # parked-in-backlog bytes still count as pending until a
            # later drain flushes them — backlog_bytes tracks that side
            with self._plock:
                self._pending -= n
        self.bytes_sent += n
        return len(batch)

    def pending_bytes(self) -> int:
        with self._plock:
            p = self._pending
        return p + self.writer.backlog_bytes

    def send(self, msg: bytes, block: bool = False) -> None:
        if self.closed:
            raise ConnectionLost("pd connection closed")
        with self._plock:
            self._pending += len(msg)
        self.outbox.append(msg)
        self.outbox.pump(block=block)

    def send_windowed(self, msg: bytes, deadline_s: float = 30.0) -> None:
        """KV-ship send: wait (bounded) for the window to open, then
        send. Raises ``KVTransferError`` when the peer cannot drain a
        window's worth within ``deadline_s`` — the request fails typed
        instead of the ship loop hanging forever on a wedged peer."""
        t_end = time.monotonic() + max(deadline_s, 0.05)
        while self.pending_bytes() + len(msg) > self.window:
            if self.closed:
                raise ConnectionLost("pd connection closed")
            if time.monotonic() >= t_end:
                raise KVTransferError(
                    f"kv ship window stalled: {self.pending_bytes()} bytes "
                    f"pending > {self.window} window")
            # try to move bytes: drain the outbox and poke the writer's
            # backlog nonblocking, then yield briefly
            self.outbox.pump(block=False)
            try:
                self.writer.write([], block=False)
            except EOFError:
                self.closed = True
                raise
            time.sleep(0.001)
        self.kv_frames += 1
        self.send(msg, block=False)

    def flush(self) -> None:
        self.outbox.pump(block=True)

    def close(self) -> None:
        self.closed = True
        try:
            self.writer.close()
        except Exception:
            pass


def hello_payload(fingerprint: str, layout) -> dict:
    return {"version": PD_VERSION, "fingerprint": fingerprint,
            "layers": int(layout.layers), "kv_heads": int(layout.kv_heads),
            "head_dim": int(layout.head_dim)}


def hello_mismatch(mine: dict, theirs: dict) -> str | None:
    """None when the peer may ship KV here; else the reason to refuse.
    Dtype/quantization may differ (the frame codec carries per-vector
    scales and the decode side rehydrates into ITS cache dtype), but
    model identity and attention geometry must match exactly — a wrong
    fingerprint would serve another model's KV as attention state."""
    if theirs.get("version") != mine["version"]:
        return f"pd protocol version {theirs.get('version')} != {mine['version']}"
    if theirs.get("fingerprint") != mine["fingerprint"]:
        return "model fingerprint mismatch"
    for k in ("layers", "kv_heads", "head_dim"):
        if theirs.get(k) != mine[k]:
            return f"kv layout mismatch: {k} {theirs.get(k)} != {mine[k]}"
    return None
