"""Decode-side KV ingest: the server half of disaggregated serving.

A decode worker (``TPU_SERVING_ROLE=decode``) owns the slot lattice and
the token stream; this listener is its admission path for prefill
workers' shipped KV. Per connection: a handshake (model fingerprint +
attention geometry must match — see ``protocol.hello_mismatch``), then
a reader loop that assembles each request's checksummed block frames
host-side as they land (overlapping the peer's prefill compute and the
wire transfer), validates EVERY frame with ``quant.decode_block``
before any byte approaches the device, and at ``KV_EOF`` submits the
assembled prompt KV to the generation engine's ingest path
(``generate(ingest=...)``) — which installs the rows under an
``hbm`` stage lease and enters the normal decode loop with zero
prefill FLOPs on this worker.

Failure contract (docs/advanced-guide/disaggregated-serving.md):

  - a truncated / checksum-failing / mis-shaped frame fails the ONE
    request with a typed 502 (``KVTransferError``) — the assembly is
    dropped host-side, no pool row was touched, the ingest loop and
    every other request keep going;
  - decode-side ``HBMExhausted`` (the arbiter cannot cover the ingest
    stage lease or the admission checkpoint) surfaces as the same
    429 + Retry-After shed every local request gets, relayed typed to
    the prefill worker and on to the client;
  - deadline expiry after the handoff fails the request with 504 and a
    ``where=post-handoff`` wide event on THIS worker;
  - a dying connection cancels that connection's streams (slots free
    within a reap) and nothing else — prefill workers reconnect and
    resume; a decode-side DeviceLost recovery fails in-flight streams
    typed through the same ERR path while the listener stays up.
"""

from __future__ import annotations

import socket
import threading
import time

import numpy as np

from .. import chaos
from ..resilience import Deadline
from ..tpu.kvcache import KVLayout
from ..tpu.kvcache.quant import concat_blocks, decode_block
from ..wire import observe_backlog
from . import protocol as p


class _Assembly:
    """One request's frames between REQ and KV_EOF — host numpy only;
    nothing touches the engine until the last frame validated."""

    __slots__ = ("meta", "deadline", "parts", "next_start", "t0",
                 "recv_wall")

    def __init__(self, meta: dict):
        self.meta = meta
        # the transfer burns the caller's budget: the deadline starts
        # at REQ receipt, so a slow ship expires HERE (post-handoff),
        # not after wasting a decode slot
        d = meta.get("deadline_s")
        self.deadline = Deadline.after(float(d)) if d else None
        self.parts: list = []
        self.next_start = 0
        self.t0 = time.monotonic()
        # wall stamp of REQ receipt: echoed in END beside the peer's
        # sent_wall so every relayed request is a clock sample
        self.recv_wall = time.time()


class KVIngestServer:
    """Listens on ``TPU_PD_LISTEN``; one reader thread per prefill-peer
    connection, one waiter thread per live ingest stream (the token
    sink itself runs zero-handoff on the serving loop thread via
    ``PushStream.set_sink``)."""

    def __init__(self, generator, fingerprint: str, host: str, port: int,
                 *, logger=None, metrics=None,
                 window_bytes: int = 8 << 20):
        self.gen = generator
        self.fingerprint = fingerprint
        self.logger = logger
        self.metrics = metrics
        self.window_bytes = int(window_bytes)
        cache = generator.cache
        self.layout = KVLayout(
            generator.cfg.n_layers, generator.cfg.n_kv_heads,
            generator.cfg.head_dim, cache.k_scale is not None,
            np.dtype(str(cache.k.dtype)), generator.max_seq)
        self._hello = p.hello_payload(fingerprint, self.layout)
        # metrics/debug port of THIS process, advertised in HELLO_OK so
        # prefill peers learn where the /debug surface lives (set by
        # App.run once the metrics server binds; None when standalone)
        self.debug_port: int | None = None
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, int(port)))
        self._sock.listen(16)
        self.host, self.port = self._sock.getsockname()[:2]
        self._closed = False
        self._conns: set = set()
        self._lock = threading.Lock()
        self.ingests = 0
        self.frame_rejects = 0
        self.refused_hellos = 0
        self.errors = 0
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="gofr-pd-ingest", daemon=True)
        self._accept_thread.start()

    # -- lifecycle -----------------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                sock, addr = self._sock.accept()
            except OSError:
                return  # listener closed
            conn = p.Conn(sock, window_bytes=self.window_bytes)
            with self._lock:
                self._conns.add(conn)
            t = threading.Thread(target=self._serve_conn,
                                 args=(conn, addr),
                                 name=f"gofr-pd-conn-{addr[1]}",
                                 daemon=True)
            t.start()

    def close(self) -> None:
        self._closed = True
        try:
            # wake a blocked accept(): close alone doesn't on Linux
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        try:
            # poke for platforms where shutdown on a listener no-ops
            poke = socket.create_connection((self.host, self.port),
                                            timeout=0.2)
            poke.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns)
        for c in conns:
            c.close()
        self._accept_thread.join(timeout=2.0)

    def stats(self) -> dict:
        with self._lock:
            n = len(self._conns)
        return {"listening": not self._closed, "port": self.port,
                "connections": n, "ingests": self.ingests,
                "frame_rejects": self.frame_rejects,
                "refused_hellos": self.refused_hellos,
                "errors": self.errors}

    # -- per-connection reader ----------------------------------------------
    def _serve_conn(self, conn: p.Conn, addr) -> None:
        pending: dict[int, _Assembly] = {}
        streams: dict[int, object] = {}
        try:
            msg = p.read_msg(conn.sock)
            t1 = time.time()  # HELLO receipt: the NTP sample's t1
            if msg is None or msg[0] != p.HELLO:
                return
            import json

            theirs = json.loads(bytes(msg[2]))
            reason = p.hello_mismatch(self._hello, theirs)
            if reason is not None:
                self.refused_hellos += 1
                if self.logger is not None:
                    self.logger.warn({"event": "pd ingest hello refused",
                                      "peer": str(addr), "reason": reason})
                conn.send(p.pack_json(p.ERR, 0, {
                    "code": 400, "message": f"hello refused: {reason}"}),
                    block=True)
                return
            # clock piggyback: HELLO_OK carries this side's receive/send
            # stamps (hello_mismatch checks only identity keys, so old
            # peers ignore the extras) plus the debug-surface port
            conn.send(p.pack_json(p.HELLO_OK, 0, dict(
                self._hello, clock_t1=t1, clock_t2=time.time(),
                debug_port=self.debug_port)), block=True)
            if self.logger is not None:
                self.logger.info({"event": "pd ingest peer connected",
                                  "peer": str(addr)})
            while not self._closed:
                msg = p.read_msg(conn.sock)
                if msg is None:
                    return
                mtype, req_id, payload = msg
                if mtype == p.REQ:
                    import json

                    pending[req_id] = _Assembly(json.loads(bytes(payload)))
                elif mtype == p.KV:
                    self._on_kv(conn, req_id, payload, pending)
                elif mtype == p.KV_EOF:
                    import json

                    self._on_eof(conn, req_id, json.loads(bytes(payload)),
                                 pending, streams)
                elif mtype == p.CANCEL:
                    pending.pop(req_id, None)
                    st = streams.pop(req_id, None)
                    if st is not None:
                        st.cancel()
                # anything else: ignore (forward compatibility)
        except Exception as e:  # noqa: BLE001 — one conn must never kill
            # the listener; its requests are failed below
            self.errors += 1
            if self.logger is not None:
                self.logger.warn({"event": "pd ingest connection failed",
                                  "peer": str(addr), "error": repr(e)})
        finally:
            # the prefill peer is gone: every live stream it owned is
            # cancelled (slots free within a reap); queued assemblies
            # are garbage — nothing touched the device for them
            for st in streams.values():
                try:
                    st.cancel()
                except Exception:
                    pass
            conn.close()
            with self._lock:
                self._conns.discard(conn)

    def _reject(self, conn: p.Conn, req_id: int, pending: dict,
                message: str) -> None:
        """Fail ONE request at the transfer boundary: typed 502, the
        assembly dropped host-side — no pool row was written, the
        reader loop continues with every other request intact."""
        self.frame_rejects += 1
        pending.pop(req_id, None)
        if self.metrics is not None:
            try:
                self.metrics.increment_counter(
                    "app_tpu_pd_frame_rejects_total")
            except Exception:
                pass
        if self.logger is not None:
            self.logger.warn({"event": "pd kv frame rejected",
                              "req_id": req_id, "reason": message})
        try:
            conn.send(p.pack_json(p.ERR, req_id, p.error_to_wire(
                p.KVTransferError(message))), block=True)
        except Exception:
            pass

    def _on_kv(self, conn: p.Conn, req_id: int, payload,
               pending: dict) -> None:
        asm = pending.get(req_id)
        if asm is None:
            return  # already failed/cancelled: drain silently
        try:
            chaos.fire(chaos.PD_INGEST)
        except Exception as e:
            # an injected fault is THIS transfer's fault: typed 502 to
            # the prefill peer, the reader loop keeps serving
            self._reject(conn, req_id, pending,
                         f"injected ingest fault: {e}")
            return
        start, frame = p.unpack_kv(payload)
        kv = decode_block(frame, self.layout)
        if kv is None:
            self._reject(conn, req_id, pending,
                         "kv frame failed validation (checksum/layout/"
                         "truncation)")
            return
        if start != asm.next_start:
            self._reject(conn, req_id, pending,
                         f"kv frame out of order: start {start} != "
                         f"expected {asm.next_start}")
            return
        asm.parts.append(kv)
        asm.next_start += kv.plen
        if self.metrics is not None:
            try:
                self.metrics.increment_counter("app_tpu_pd_kv_frames_total",
                                               direction="in")
            except Exception:
                pass

    def _on_eof(self, conn: p.Conn, req_id: int, eof: dict,
                pending: dict, streams: dict) -> None:
        asm = pending.pop(req_id, None)
        if asm is None:
            return
        meta = asm.meta
        plen = int(meta.get("plen", 0))
        if not asm.parts or asm.next_start != plen:
            self._reject(conn, req_id, pending,
                         f"kv transfer incomplete: {asm.next_start}/{plen} "
                         "tokens received")
            return
        prompt = np.asarray(meta["prompt"], np.int32)
        # durable streams: a re-handoff REQ carries the already-
        # delivered tokens — the engine admits prompt+emitted as ONE
        # continuation prompt (the shipped KV covers the concat), and
        # the REQ's pinned seed keeps sampled continuations resume-
        # exact (the PRNG re-keys on absolute token position)
        emitted = [int(t) for t in (meta.get("resume_emitted") or [])]
        seed = meta.get("seed")
        try:
            kv = concat_blocks(asm.parts)
            eos = meta.get("eos")
            stream = self.gen.generate(
                prompt,
                max_new_tokens=int(meta.get("max_new", 128)),
                temperature=float(meta.get("temperature", 0.0)),
                top_k=int(meta.get("top_k", 0)),
                eos_id=eos if eos is None or isinstance(eos, int) else
                frozenset(int(t) for t in eos),
                adapter=int(meta.get("adapter", 0)),
                logprobs=True,
                deadline=asm.deadline,
                slo_class=meta.get("slo_class"),
                seed=int(seed) if seed is not None else None,
                continue_from=(prompt, emitted) if emitted else None,
                ingest=(kv, int(eof["first_token"]),
                        float(eof.get("first_lp") or 0.0)),
                traceparent=meta.get("traceparent"))
        except BaseException as e:  # noqa: BLE001 — typed relay: sheds
            # stay 429, deadline stays 504, the engine stays alive
            self.errors += 1
            try:
                conn.send(p.pack_json(p.ERR, req_id, p.error_to_wire(e)),
                          block=True)
            except Exception:
                pass
            return
        self.ingests += 1
        try:
            # the wire+assembly segment of the critical path: REQ
            # receipt to the engine accepting the installed rows. It
            # PRECEDES the stream's submit stamp, so the wide event
            # carries it beside the breakdown, not inside it.
            stream.trace["kv_transfer_s"] = round(
                time.monotonic() - asm.t0, 6)
        except Exception:
            pass  # telemetry must never fail the ingest
        if self.metrics is not None:
            try:
                self.metrics.increment_counter("app_tpu_pd_requests_total",
                                               role="decode")
            except Exception:
                pass
        streams[req_id] = stream
        threading.Thread(target=self._relay_stream,
                         args=(conn, req_id, stream, streams, asm),
                         name=f"gofr-pd-stream-{req_id}",
                         daemon=True).start()

    def _end_payload(self, sent: int, stream, asm) -> dict:
        """The END frame doubles as the return leg of a per-request
        clock sample (sent_wall echoed beside this side's REQ-receipt
        and END-send stamps) and carries the decode worker's segment
        view so the prefill side can tell the whole story."""
        endp: dict = {"tokens": sent}
        try:
            endp["req_sent_wall"] = asm.meta.get("sent_wall")
            endp["req_recv_wall"] = asm.recv_wall
            endp["end_sent_wall"] = time.time()
            tr = getattr(stream, "trace", None) or {}
            bd: dict = {}
            now = time.monotonic()
            for seg, a, b in (("queue_wait", tr.get("submit"),
                               tr.get("admit")),
                              ("prefill", tr.get("admit"),
                               tr.get("prefill_done")),
                              ("handoff", tr.get("prefill_done"),
                               tr.get("first_put")),
                              ("decode", tr.get("first_put"), now)):
                if a is not None and b is not None:
                    bd[seg + "_s"] = round(max(0.0, b - a), 6)
            if tr.get("kv_transfer_s") is not None:
                bd["kv_transfer_s"] = tr["kv_transfer_s"]
            if bd:
                endp["breakdown"] = bd
        except Exception:
            pass  # a bare {"tokens": n} END is always valid
        return endp

    def _relay_stream(self, conn: p.Conn, req_id: int, stream,
                      streams: dict, asm: _Assembly | None = None) -> None:
        """Token relay for one ingested stream: tokens leave zero-
        handoff on the serving loop thread (PushStream sink -> Outbox,
        nonblocking); this waiter only observes the terminal outcome
        and sends END/ERR with a blocking flush."""
        # the FIRST delivered token is skipped: the prefill worker
        # sampled it and already delivered it to the client (TTFT is
        # the prefill pool's latency); this stream owns tokens 2+.
        # Each TOK carries the resume contract's monotone cursor — the
        # absolute generated-token index of the ORIGINAL request
        # (stream.cursor_base counts the continuation's replayed
        # tokens; +1 skips the prefill-delivered first token)
        base = int(getattr(stream, "cursor_base", 0) or 0) + 1
        sent = [0]
        skipped = [False]

        def sink(item) -> bool:
            if not skipped[0]:
                skipped[0] = True
                return True
            tok, lp = item if isinstance(item, tuple) else (item, None)
            conn.send(p.pack_tok(req_id, tok, base + sent[0], lp))
            sent[0] += 1
            if sent[0] % 32 == 0:
                # sampled, not per-token: the gauge is a trend line
                observe_backlog(self.metrics, conn.pending_bytes(),
                                role="pd-decode")
            return True

        stream.set_sink(sink)
        try:
            for item in stream:
                # only reached if the sink was dropped (conn hiccup):
                # forward through the blocking path
                if not skipped[0]:
                    skipped[0] = True
                    continue
                tok, lp = item if isinstance(item, tuple) else (item, None)
                conn.send(p.pack_tok(req_id, tok, base + sent[0], lp),
                          block=True)
                sent[0] += 1
            conn.send(p.pack_json(p.END, req_id,
                                  self._end_payload(sent[0], stream, asm)
                                  if asm is not None
                                  else {"tokens": sent[0]}),
                      block=True)
        except BaseException as e:  # noqa: BLE001 — relay the typed error
            try:
                conn.send(p.pack_json(p.ERR, req_id, p.error_to_wire(e)),
                          block=True)
            except Exception:
                pass
            # the relay is dead either way: CANCEL the stream so the
            # decode slot (and its paged blocks) free within a reap
            # instead of generating the rest of the budget into an
            # unread queue (_serve_conn's teardown only covers streams
            # still registered when the READER exits)
            try:
                stream.cancel()
            except Exception:
                pass
        finally:
            streams.pop(req_id, None)
