"""BERT-style bidirectional encoder producing sentence embeddings.

Serving target: BERT-base embedding endpoint (BASELINE.md config #2).
Same TPU-first layout as llama.py: stacked layers + lax.scan, functional
params, static shapes with an attention mask for padding.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ops.attention import full_attention
from ..ops.norms import layer_norm
from ..ops.quant import qmatmul
from .common import ModelConfig, dense_init


def init(cfg: ModelConfig, key) -> dict:
    dt = cfg.jdtype
    keys = jax.random.split(key, 16)
    L, D, H, hd, F, V = (cfg.n_layers, cfg.dim, cfg.n_heads,
                         cfg.dim // cfg.n_heads, cfg.ffn_dim, cfg.vocab_size)
    return {
        "embedding": dense_init(keys[0], (V, D), dt, scale=0.02),
        "pos_embedding": dense_init(keys[1], (cfg.max_seq, D), dt, scale=0.02),
        "type_embedding": dense_init(keys[2], (cfg.type_vocab_size, D), dt, scale=0.02),
        "embed_norm_w": jnp.ones((D,), dt),
        "embed_norm_b": jnp.zeros((D,), dt),
        "layers": {
            "wq": dense_init(keys[3], (L, D, H * hd), dt),
            "bq": jnp.zeros((L, H * hd), dt),
            "wk": dense_init(keys[4], (L, D, H * hd), dt),
            "bk": jnp.zeros((L, H * hd), dt),
            "wv": dense_init(keys[5], (L, D, H * hd), dt),
            "bv": jnp.zeros((L, H * hd), dt),
            "wo": dense_init(keys[6], (L, H * hd, D), dt),
            "bo": jnp.zeros((L, D), dt),
            "attn_norm_w": jnp.ones((L, D), dt),
            "attn_norm_b": jnp.zeros((L, D), dt),
            "w_in": dense_init(keys[7], (L, D, F), dt),
            "b_in": jnp.zeros((L, F), dt),
            "w_out": dense_init(keys[8], (L, F, D), dt),
            "b_out": jnp.zeros((L, D), dt),
            "ffn_norm_w": jnp.ones((L, D), dt),
            "ffn_norm_b": jnp.zeros((L, D), dt),
        },
        "pooler_w": dense_init(keys[9], (D, D), dt),
        "pooler_b": jnp.zeros((D,), dt),
    }


def encode(params: dict, cfg: ModelConfig, tokens: jnp.ndarray,
           mask: jnp.ndarray | None = None,
           token_types: jnp.ndarray | None = None) -> jnp.ndarray:
    """tokens [B, S] -> hidden states [B, S, D]."""
    B, S = tokens.shape
    if mask is None:
        mask = jnp.ones((B, S), bool)
    if token_types is None:
        token_types = jnp.zeros((B, S), jnp.int32)
    H, hd = cfg.n_heads, cfg.dim // cfg.n_heads

    x = (params["embedding"][tokens]
         + params["pos_embedding"][None, :S]
         + params["type_embedding"][token_types]).astype(cfg.jdtype)
    x = layer_norm(x, params["embed_norm_w"], params["embed_norm_b"], cfg.norm_eps)

    def body(x, w):
        q = (qmatmul(x, w["wq"]) + w["bq"]).reshape(B, S, H, hd)
        k = (qmatmul(x, w["wk"]) + w["bk"]).reshape(B, S, H, hd)
        v = (qmatmul(x, w["wv"]) + w["bv"]).reshape(B, S, H, hd)
        attn = full_attention(q, k, v, mask=mask).reshape(B, S, H * hd)
        x = layer_norm(x + qmatmul(attn, w["wo"]) + w["bo"],
                       w["attn_norm_w"], w["attn_norm_b"], cfg.norm_eps)
        h = jax.nn.gelu(qmatmul(x, w["w_in"]) + w["b_in"])
        x = layer_norm(x + qmatmul(h, w["w_out"]) + w["b_out"],
                       w["ffn_norm_w"], w["ffn_norm_b"], cfg.norm_eps)
        return x, None

    x, _ = jax.lax.scan(body, x, params["layers"])
    return x


def embed(params: dict, cfg: ModelConfig, tokens: jnp.ndarray,
          mask: jnp.ndarray | None = None) -> jnp.ndarray:
    """Mean-pooled L2-normalized sentence embeddings [B, D] (the serving
    endpoint's output)."""
    B, S = tokens.shape
    if mask is None:
        mask = jnp.ones((B, S), bool)
    x = encode(params, cfg, tokens, mask)
    m = mask[..., None].astype(x.dtype)
    pooled = (x * m).sum(axis=1) / jnp.maximum(m.sum(axis=1), 1.0)
    pooled = pooled.astype(jnp.float32)
    return pooled / jnp.maximum(jnp.linalg.norm(pooled, axis=-1, keepdims=True), 1e-9)


def pool_cls(params: dict, cfg: ModelConfig, tokens: jnp.ndarray,
             mask: jnp.ndarray | None = None) -> jnp.ndarray:
    """Classic BERT pooler: tanh(W @ h_[CLS])."""
    x = encode(params, cfg, tokens, mask)
    return jnp.tanh(qmatmul(x[:, 0], params["pooler_w"]) + params["pooler_b"])
