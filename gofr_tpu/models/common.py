"""Shared model configuration and initializer helpers."""

from __future__ import annotations

from dataclasses import dataclass, replace

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class ModelConfig:
    name: str = "custom"
    vocab_size: int = 32000
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    ffn_dim: int = 14336
    max_seq: int = 8192
    rope_theta: float = 500000.0
    rope_scaling: dict | None = None
    norm_eps: float = 1e-5
    tie_embeddings: bool = False
    dtype: str = "bfloat16"
    # encoder-model extras
    n_classes: int = 0
    image_size: int = 224
    patch_size: int = 14
    type_vocab_size: int = 2
    # mixture-of-experts (0 experts = dense FFN)
    n_experts: int = 0
    experts_per_token: int = 2
    # 0 = dense dispatch (every expert computes every token; exact, best
    # below ~8 experts); > 0 = capacity-based grouped dispatch with
    # per-expert buffer capacity factor*T*k/E (tokens over capacity drop
    # — the standard Switch/Mixtral trade at scale)
    moe_capacity_factor: float = 0.0

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @property
    def jdtype(self):
        return jnp.dtype(self.dtype)

    def with_(self, **kw) -> "ModelConfig":
        return replace(self, **kw)


LLAMA_CONFIGS = {
    # Llama-3-8B / 70B (architecture dims are public knowledge)
    "llama3-8b": ModelConfig(name="llama3-8b", vocab_size=128256, dim=4096,
                             n_layers=32, n_heads=32, n_kv_heads=8,
                             ffn_dim=14336, max_seq=8192),
    "llama3-70b": ModelConfig(name="llama3-70b", vocab_size=128256, dim=8192,
                              n_layers=80, n_heads=64, n_kv_heads=8,
                              ffn_dim=28672, max_seq=8192),
    # small variants for single-chip serving and tests
    "llama-1b": ModelConfig(name="llama-1b", vocab_size=128256, dim=2048,
                            n_layers=16, n_heads=32, n_kv_heads=8,
                            ffn_dim=8192, max_seq=8192, tie_embeddings=True),
    "tiny": ModelConfig(name="tiny", vocab_size=256, dim=64, n_layers=2,
                        n_heads=4, n_kv_heads=2, ffn_dim=128, max_seq=128,
                        rope_theta=10000.0, dtype="float32"),
    # Mixtral-8x7B (public dims): top-2 of 8 SwiGLU experts per layer
    "mixtral-8x7b": ModelConfig(name="mixtral-8x7b", vocab_size=32000,
                                dim=4096, n_layers=32, n_heads=32,
                                n_kv_heads=8, ffn_dim=14336, max_seq=8192,
                                rope_theta=1e6, n_experts=8,
                                experts_per_token=2),
    "tiny-moe": ModelConfig(name="tiny-moe", vocab_size=256, dim=64,
                            n_layers=2, n_heads=4, n_kv_heads=2,
                            ffn_dim=128, max_seq=128, rope_theta=10000.0,
                            dtype="float32", n_experts=4,
                            experts_per_token=2),
}

BERT_CONFIGS = {
    "bert-base": ModelConfig(name="bert-base", vocab_size=30522, dim=768,
                             n_layers=12, n_heads=12, n_kv_heads=12,
                             ffn_dim=3072, max_seq=512, norm_eps=1e-12),
    "tiny": ModelConfig(name="tiny-bert", vocab_size=128, dim=64, n_layers=2,
                        n_heads=4, n_kv_heads=4, ffn_dim=128, max_seq=64,
                        norm_eps=1e-12, dtype="float32"),
}

VIT_CONFIGS = {
    "vit-l-14": ModelConfig(name="vit-l-14", dim=1024, n_layers=24,
                            n_heads=16, n_kv_heads=16, ffn_dim=4096,
                            image_size=224, patch_size=14, n_classes=1000),
    "tiny": ModelConfig(name="tiny-vit", dim=64, n_layers=2, n_heads=4,
                        n_kv_heads=4, ffn_dim=128, image_size=28,
                        patch_size=14, n_classes=10, dtype="float32"),
}


def dense_init(key, shape, dtype, scale: float | None = None) -> jnp.ndarray:
    """Truncated-normal fan-in init."""
    fan_in = shape[-2] if len(shape) >= 2 else shape[-1]
    std = scale if scale is not None else fan_in ** -0.5
    return (jax.random.truncated_normal(key, -2.0, 2.0, shape, jnp.float32) * std).astype(dtype)


def sample_logits(logits: jnp.ndarray, key, temperature: float = 0.0,
                  top_k: int = 0) -> jnp.ndarray:
    """Sample token ids from [B, V] logits. temperature<=0 -> greedy.
    Shape-static (top_k is a python int) so it jits once."""
    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits.astype(jnp.float32) / temperature
    if top_k > 0:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -1e30, logits)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)
