"""Paged (block-pool) KV cache for Llama-family serving.

The contiguous ``llama.KVCache`` reserves [B, Smax] rows per slot; HBM
capacity caps the decode batch long before the MXU or the weight stream
does (8B int8 at batch 128 x 1024: ~9.7 GB KV on top of 8 GB weights —
over a v5e's 16 GB). This module keeps the same model math (the layer
scan calls the SAME ``llama._layer``) but stores KV in a shared pool of
fixed T-token blocks with a per-slot block table:

    k_pool/v_pool  [L, N, T, KV, hd]   (int8 with [L, N, T, KV] scales)
    table          [B, MB] int32       host-owned, passed per dispatch
    lengths        [B]    int32        device state, donated

TPU-first constraints drive every choice: N/T/MB are static so one
program serves all occupancies; the table is data, not shape; block
boundaries are crossed with host-side allocation between fused decode
blocks (the device never allocates); attention runs the scalar-prefetch
Pallas kernel (ops.paged_attention) whose HBM stream is proportional to
LIVE tokens, with a dense-gather jnp reference for CPU/tests.

Table invariants (maintained by the engine's allocator):
  - entries for live logical blocks hold real pool block ids;
  - entries past the live range repeat the LAST live block (clamping —
    the kernel's DMA-skip), or block 0 for empty/retired slots;
  - block 0 is a reserved trash block no slot ever owns: retired slots'
    frozen-cursor garbage writes land there.

Reference provenance: the reference (GoFr) is a pure-Go microservice
framework with zero ML code — paged serving has NO reference
counterpart. This module implements the TPU-inference rows SURVEY.md §2
adds to the inventory (the "to build — native" rows); the design is
cross-checked against the public PagedAttention idea, rebuilt for
static shapes + Mosaic.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..ops.paged_attention import paged_attention_auto
from . import llama
from .common import ModelConfig
from .llama import (_layer, _logits, get_rope_tables,
                    multi_request_serving_config, quantize_kv)


class PagedKVCache(NamedTuple):
    k: jnp.ndarray        # [L, N, T, KV, hd]
    v: jnp.ndarray        # [L, N, T, KV, hd]
    lengths: jnp.ndarray  # [B] int32 — live tokens per slot
    k_scale: jnp.ndarray | None = None  # [L, N, T, KV] f32 (int8 pools)
    v_scale: jnp.ndarray | None = None

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None

    @property
    def block_size(self) -> int:
        return self.k.shape[2]

    @property
    def n_blocks(self) -> int:
        return self.k.shape[1]


def init_paged_cache(cfg: ModelConfig, slots: int, n_blocks: int,
                     block_size: int = 128, dtype=None) -> PagedKVCache:
    """Pool of ``n_blocks`` blocks (block 0 is the reserved trash block —
    size the pool as usable_tokens // block_size + 1). ``dtype=jnp.int8``
    allocates the quantized pool with scale planes."""
    dtype = dtype or cfg.jdtype
    shape = (cfg.n_layers, n_blocks, block_size, cfg.n_kv_heads,
             cfg.head_dim)
    quant = jnp.dtype(dtype) == jnp.int8
    return PagedKVCache(
        k=jnp.zeros(shape, dtype),
        v=jnp.zeros(shape, dtype),
        lengths=jnp.zeros((slots,), jnp.int32),
        k_scale=jnp.zeros(shape[:-1], jnp.float32) if quant else None,
        v_scale=jnp.zeros(shape[:-1], jnp.float32) if quant else None,
    )



def _pool_coords(table: jnp.ndarray, positions: jnp.ndarray, T: int,
                 ) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(block_ids, offsets) for writing at ``positions`` ([B] or [B, W])
    through a clamped ``table`` [B, MB]. Past-capacity positions route
    to the trash block — the paged mirror of the contiguous scatter's
    mode="drop" (without it the offset would wrap into the slot's own
    live last block)."""
    mb = table.shape[1]
    idx = jnp.minimum(positions // T, mb - 1)
    blk = jnp.take_along_axis(table, idx if idx.ndim == 2 else idx[:, None],
                              axis=1)
    if positions.ndim == 1:
        blk = blk[:, 0]
    blk = jnp.where(positions < mb * T, blk, 0)
    return blk, positions % T


def paged_decode_step(params: dict, cfg: ModelConfig, tokens: jnp.ndarray,
                      cache: PagedKVCache, table: jnp.ndarray,
                      rope_tables=None, flash: bool = True,
                      adapter=None, mesh=None
                      ) -> tuple[jnp.ndarray, PagedKVCache]:
    """One decode step for tokens [B] against the paged pool.

    ``table`` [B, MB] int32: clamped block ids (see module docstring).
    Returns (logits [B, V] f32, cache with lengths+1). Same structure as
    llama.decode_step (reference hot loop): pool READ-ONLY inside the
    layer scan, the new token's [L, B, KV, hd] written by one scatter
    after it.

    CAPACITY CONTRACT: the caller guarantees each slot's current block
    (table[b, lengths[b] // T]) is allocated and lengths < MB*T; the
    write position is clamped into the table's range, so a violated
    contract corrupts only that slot's own (or the trash) block.
    ``flash=False`` routes attention through the dense-gather reference
    (CPU tests; the kernel gate also falls back off-TPU). With ``mesh``
    the kernel runs under shard_map per tp head shard — no dense pool
    gather on mesh (ops.paged_attention.paged_decode_sharded)."""
    cfg = multi_request_serving_config(cfg)
    B = tokens.shape[0]
    T = cache.block_size
    mb = table.shape[1]
    max_seq = mb * T
    cos, sin = rope_tables or get_rope_tables(cfg, max_seq)
    positions = cache.lengths[:, None]
    lengths = cache.lengths

    x = params["embedding"][tokens[:, None]].astype(cfg.jdtype)

    if flash:
        import functools

        attn = functools.partial(paged_attention_auto, mesh=mesh)
    else:
        attn = _reference_attention

    def body(x, xs):
        layer_w, k_layer, v_layer, ks_layer, vs_layer = xs

        def attend(q, k_new, v_new):
            return attn(q, k_layer, v_layer, k_new, v_new, table,
                        lengths, ks_layer, vs_layer)

        x, kv_tok, _ = _layer(x, layer_w, cfg, cos, sin, positions,
                              kv_write=lambda k, v: (k, v), attend=attend,
                              adapter=adapter)
        return x, kv_tok

    x, (k_toks, v_toks) = jax.lax.scan(
        body, x, (params["layers"], cache.k, cache.v,
                  cache.k_scale, cache.v_scale))
    # one scatter for all layers into each slot's current block
    blk, off = _pool_coords(table, lengths, T)
    k_tok, v_tok = k_toks[:, :, 0], v_toks[:, :, 0]      # [L, B, KV, hd]
    if cache.quantized:
        qk, sk = quantize_kv(k_tok)
        qv, sv = quantize_kv(v_tok)
        new = cache._replace(
            k=cache.k.at[:, blk, off].set(qk, mode="drop"),
            v=cache.v.at[:, blk, off].set(qv, mode="drop"),
            k_scale=cache.k_scale.at[:, blk, off].set(sk, mode="drop"),
            v_scale=cache.v_scale.at[:, blk, off].set(sv, mode="drop"),
            lengths=lengths + 1)
    else:
        new = cache._replace(
            k=cache.k.at[:, blk, off].set(k_tok.astype(cache.k.dtype),
                                          mode="drop"),
            v=cache.v.at[:, blk, off].set(v_tok.astype(cache.v.dtype),
                                          mode="drop"),
            lengths=lengths + 1)
    return _logits(params, cfg, x[:, 0]), new


def _reference_attention(q, k_pool, v_pool, k_new, v_new, table, lengths,
                         k_scale, v_scale):
    from ..ops.paged_attention import paged_attention_reference

    return paged_attention_reference(q, k_pool, v_pool, k_new, v_new,
                                     table, lengths, k_scale, v_scale)


def paged_verify_step(params: dict, cfg: ModelConfig, tokens: jnp.ndarray,
                      cache: PagedKVCache, table: jnp.ndarray,
                      rope_tables=None, adapter=None, flash: bool = True,
                      mesh=None) -> tuple[jnp.ndarray, PagedKVCache]:
    """Speculative-decoding verify pass over the paged pool — the exact
    contract of llama.verify_step (logits [B, W, V]; lengths returned
    UNCHANGED, acceptance is the caller's; W KV rows written at each
    slot's cursor), with the pool addressed through ``table``.

    Attention runs the paged WINDOW kernel (ops.paged_attention.
    paged_window_auto): the cache side streams each slot's live blocks
    exactly once through the same scalar-prefetch kernel as decode, and
    the W x W in-window part folds in exactly — off-TPU the auto gate
    falls back to window_attention_appended over a dense gather of the
    table. ``flash=False`` forces that dense-gather reference. With
    ``mesh`` the kernel runs under shard_map per tp head shard
    (ops.paged_attention.paged_window_sharded) — speculative decoding
    keeps the kernel, and the no-dense-gather rule, on mesh engines.

    CAPACITY CONTRACT (same as verify_step): callers must only honor
    acceptance for slots with lengths + W <= capacity; rows past
    capacity route to the trash block, mirroring the contiguous
    scatter's mode=\"drop\"."""
    from ..ops.paged_attention import paged_window_auto

    cfg = multi_request_serving_config(cfg)
    B, W = tokens.shape
    T = cache.block_size
    mb = table.shape[1]
    cos, sin = rope_tables or get_rope_tables(cfg, mb * T)
    positions = cache.lengths[:, None] + jnp.arange(W, dtype=jnp.int32)[None, :]
    lengths = cache.lengths

    x = params["embedding"][tokens].astype(cfg.jdtype)  # [B, W, D]

    def body(x, xs):
        layer_w, k_layer, v_layer, ks_layer, vs_layer = xs

        def attend(q, k_new, v_new):
            if not flash:
                from ..ops.paged_attention import paged_window_reference

                return paged_window_reference(
                    q, k_layer, v_layer, k_new, v_new, table, lengths,
                    ks_layer, vs_layer)
            return paged_window_auto(q, k_layer, v_layer, k_new, v_new,
                                     table, lengths, ks_layer, vs_layer,
                                     mesh=mesh)

        x, kv, _ = _layer(x, layer_w, cfg, cos, sin, positions,
                          kv_write=lambda k, v: (k, v), attend=attend,
                          adapter=adapter)
        return x, kv

    x, (k_w, v_w) = jax.lax.scan(
        body, x, (params["layers"], cache.k, cache.v,
                  cache.k_scale, cache.v_scale))
    # one scatter for all layers and window rows into pool coordinates
    blk, off = _pool_coords(table, positions, T)
    if cache.quantized:
        qk, sk = quantize_kv(k_w)
        qv, sv = quantize_kv(v_w)
        new = cache._replace(
            k=cache.k.at[:, blk, off].set(qk),
            v=cache.v.at[:, blk, off].set(qv),
            k_scale=cache.k_scale.at[:, blk, off].set(sk),
            v_scale=cache.v_scale.at[:, blk, off].set(sv),
            lengths=lengths)
    else:
        new = cache._replace(
            k=cache.k.at[:, blk, off].set(k_w.astype(cache.k.dtype)),
            v=cache.v.at[:, blk, off].set(v_w.astype(cache.v.dtype)),
            lengths=lengths)
    return _logits(params, cfg, x), new


def write_prompt_blocks(cache: PagedKVCache, k_stack, v_stack,
                        blocks: jnp.ndarray, length) -> PagedKVCache:
    """Write one admitted prompt's KV stacks [L, 1, S, KV, hd] into its
    allocated blocks. ``blocks`` [ceil(S/T)] int32 (traced values, static
    count — one program per prompt bucket); ``length`` is the true prompt
    length: rows in [length, S) are bucket padding — they land in the
    slot's own blocks past its cursor, invisible behind ``lengths`` and
    overwritten as decode advances (the same contract as the contiguous
    cache's write_kv). Quantize-on-write, then one shared block-copy
    loop (write_row_to_blocks) moves the rows."""
    if cache.quantized:
        qk, sk = quantize_kv(k_stack)
        qv, sv = quantize_kv(v_stack)
        row = llama.KVCache(k=qk, v=qv, lengths=None, k_scale=sk,
                            v_scale=sv)
    else:
        row = llama.KVCache(k=k_stack, v=v_stack, lengths=None)
    return write_row_to_blocks(cache, row, blocks)


def read_blocks_to_row(row, cache: PagedKVCache,
                       blocks: jnp.ndarray):
    """Inverse of write_row_to_blocks: gather pool blocks into a dense
    single-slot scratch row [L, 1, Smax, KV, hd] — the restore half of
    the paged prefix cache (shared blocks -> scratch, then chunked
    prefill resumes from the match point against the dense row).
    ``blocks`` [MB] int32: entries past the shared prefix may point
    anywhere (typically the trash block); those positions are
    overwritten by the resumed chunks or ignored past the prompt."""
    T = cache.block_size
    mb = blocks.shape[0]
    k, v, ks, vs = row.k, row.v, row.k_scale, row.v_scale
    quant = cache.quantized
    for j in range(mb):
        lo = j * T
        span = min(T, k.shape[2] - lo)
        if span <= 0:
            break
        blk_k = jax.lax.dynamic_slice(
            cache.k, (0, blocks[j], 0, 0, 0),
            (cache.k.shape[0], 1, span) + cache.k.shape[3:])
        blk_v = jax.lax.dynamic_slice(
            cache.v, (0, blocks[j], 0, 0, 0),
            (cache.v.shape[0], 1, span) + cache.v.shape[3:])
        k = jax.lax.dynamic_update_slice(k, blk_k.astype(k.dtype),
                                         (0, 0, lo, 0, 0))
        v = jax.lax.dynamic_update_slice(v, blk_v.astype(v.dtype),
                                         (0, 0, lo, 0, 0))
        if quant:
            sk = jax.lax.dynamic_slice(
                cache.k_scale, (0, blocks[j], 0, 0),
                (cache.k_scale.shape[0], 1, span, cache.k_scale.shape[3]))
            sv = jax.lax.dynamic_slice(
                cache.v_scale, (0, blocks[j], 0, 0),
                (cache.v_scale.shape[0], 1, span, cache.v_scale.shape[3]))
            ks = jax.lax.dynamic_update_slice(ks, sk, (0, 0, lo, 0))
            vs = jax.lax.dynamic_update_slice(vs, sv, (0, 0, lo, 0))
    return row._replace(k=k, v=v, k_scale=ks, v_scale=vs)


def write_row_to_blocks(cache: PagedKVCache, row, blocks: jnp.ndarray,
                        ) -> PagedKVCache:
    """Copy a dense single-slot cache row (llama.KVCache with B=1,
    [L, 1, S, KV, hd]; S may be shorter than MB*T — slices clamp) into
    pool blocks. The shared block-copy loop under BOTH admission paths:
    write_prompt_blocks quantizes a prefill's stacks into a row and
    delegates here; long-prompt admission lands the chunked SCRATCH row
    directly. ``blocks`` [n] int32: entries past the prompt's own
    blocks point at the trash block, so positions beyond the prompt
    land nowhere. Same-dtype copy (int8 + scales move verbatim)."""
    T = cache.block_size
    mb = blocks.shape[0]
    k, v, ks, vs = cache.k, cache.v, cache.k_scale, cache.v_scale
    quant = cache.quantized
    for j in range(mb):
        lo = j * T
        k = jax.lax.dynamic_update_slice(
            k, row.k[:, 0, lo:lo + T][:, None].astype(k.dtype),
            (0, blocks[j], 0, 0, 0))
        v = jax.lax.dynamic_update_slice(
            v, row.v[:, 0, lo:lo + T][:, None].astype(v.dtype),
            (0, blocks[j], 0, 0, 0))
        if quant:
            ks = jax.lax.dynamic_update_slice(
                ks, row.k_scale[:, 0, lo:lo + T][:, None],
                (0, blocks[j], 0, 0))
            vs = jax.lax.dynamic_update_slice(
                vs, row.v_scale[:, 0, lo:lo + T][:, None],
                (0, blocks[j], 0, 0))
    return cache._replace(k=k, v=v, k_scale=ks, v_scale=vs)


class BlockAllocator:
    """Host-side refcounted free-list over pool blocks 1..N-1 (block 0
    is the reserved trash block). Refcounts exist for SHARED prefix
    blocks: a stored prefix entry and every slot serving from it each
    hold a reference; a block returns to the free list only when the
    last holder drops it. Thread-compatible: the engine calls it only
    from the serving loop under its device lock."""

    def __init__(self, n_blocks: int):
        if n_blocks < 2:
            raise ValueError("paged pool needs >= 2 blocks "
                             "(block 0 is reserved)")
        import numpy as np

        self._free = list(range(n_blocks - 1, 0, -1))
        self._rc = np.zeros(n_blocks, np.int32)
        self.n_blocks = n_blocks

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    def alloc(self, n: int) -> list[int] | None:
        """n block ids (each at refcount 1), or None (nothing allocated)
        if the pool can't cover the request — the caller picks the
        eviction policy."""
        if n > len(self._free):
            return None
        out = [self._free.pop() for _ in range(n)]
        for b in out:
            self._rc[b] = 1
        return out

    def ref(self, blocks) -> None:
        """Additional holder for already-allocated blocks."""
        for b in blocks:
            assert self._rc[b] > 0, f"ref of unallocated block {b}"
            self._rc[b] += 1

    def free(self, blocks) -> None:
        """Drop one reference per block; blocks with no remaining holder
        return to the free list."""
        for b in blocks:
            self._rc[b] -= 1
            if self._rc[b] == 0:
                self._free.append(b)
            assert self._rc[b] >= 0, f"double free of block {b}"

    def sole_holder(self, blocks) -> bool:
        """True when the caller's reference is the only one on every
        block — freeing would return them all to the free list."""
        return all(self._rc[b] == 1 for b in blocks)


class SharedPrefixIndex:
    """Zero-copy prefix reuse for the paged pool (the paged counterpart
    of the contiguous engine's tpu/kvcache hierarchy — here the pool
    blocks ARE the storage, so there is nothing to tier): entries
    record the FULL T-token
    blocks of a stored prompt prefix and hold a reference on each — no
    KV is ever copied to store. Full blocks are immutable once written
    (decode only ever writes the block at a slot's cursor, which lies
    past its prompt's full blocks), so a stored entry stays valid for
    any continuation; a hit refs the shared blocks into the new slot's
    table and prefill resumes at the match point. Matches clamp to
    whole blocks and never consume the entire prompt (>= 1 token always
    recomputes, mirroring the contiguous engine's contract). LRU
    entries are evictable under pool pressure — eviction just drops the
    entry's references. Thread-compatible: serving-loop only."""

    def __init__(self, max_entries: int, alloc: BlockAllocator,
                 block_size: int):
        self.max_entries = int(max_entries)
        self._alloc = alloc
        self._t = int(block_size)
        self._entries: list[dict] = []  # {key, blocks, adapter, used}
        self._tick = 0
        self.hits = 0
        self.misses = 0
        # bumped on every mutation that can change a match() outcome —
        # lets callers memoize peek results (the serving loop polls
        # _needs_lattice every ~2 ms while a request heads the queue;
        # re-scanning an unchanged index is pure waste)
        self.version = 0

    def __len__(self) -> int:
        return len(self._entries)

    def match(self, prompt, adapter: int = 0) -> tuple[list[int], int]:
        """(shared_blocks, matched_tokens) — the longest stored LCP,
        clamped to whole blocks and to len(prompt)-1. ([], 0) on miss.
        PURE like PrefixIndex.match: accept()/reject() report back."""
        import numpy as np

        prompt = np.asarray(prompt, np.int32)
        limit = (len(prompt) - 1) // self._t  # blocks fully reusable
        best, best_blocks = 0, []
        for e in self._entries:
            if e["adapter"] != adapter:
                continue
            key = e["key"]
            n = min(len(key), len(prompt))
            neq = np.nonzero(key[:n] != prompt[:n])[0]
            m = int(neq[0]) if len(neq) else n
            nb = min(m // self._t, limit)
            if nb * self._t > best:
                best = nb * self._t
                best_blocks = e["blocks"][:nb]
        return (list(best_blocks), best) if best else ([], 0)

    def accept(self, blocks: list[int]) -> None:
        """A hit went live: count it, touch the owning entry's LRU."""
        self.hits += 1
        self._tick += 1
        lead = blocks[0] if blocks else -1
        for e in self._entries:
            if e["blocks"] and e["blocks"][0] == lead:
                e["used"] = self._tick

    def reject(self) -> None:
        self.misses += 1

    def covered(self, prompt, adapter: int = 0) -> bool:
        """True when some entry already stores >= this prompt's full
        blocks with identical tokens — storing again would only
        duplicate references."""
        import numpy as np

        prompt = np.asarray(prompt, np.int32)
        n_full = len(prompt) // self._t
        if n_full == 0:
            return True  # nothing storable
        head = prompt[:n_full * self._t]
        for e in self._entries:
            if e["adapter"] == adapter and len(e["key"]) >= len(head) \
                    and np.array_equal(e["key"][:len(head)], head):
                return True
        return False

    def store(self, prompt, blocks: list[int], adapter: int = 0) -> None:
        """Record ``prompt``'s full blocks as an entry, holding one
        reference on each (zero-copy: the blocks are the slot's own,
        already written). Evicts LRU entries past capacity."""
        import numpy as np

        prompt = np.asarray(prompt, np.int32)
        n_full = len(prompt) // self._t
        if n_full == 0:
            return
        held = list(blocks[:n_full])
        self._alloc.ref(held)
        self._tick += 1
        self.version += 1
        self._entries.append({"key": prompt[:n_full * self._t].copy(),
                              "blocks": held, "adapter": int(adapter),
                              "used": self._tick})
        while len(self._entries) > self.max_entries:
            self.evict_one()

    def evict_one(self) -> bool:
        """Drop one entry's references (pool-pressure valve). Returns
        False when there is nothing left to evict.

        Prefers the LRU entry among those whose blocks will ACTUALLY
        return to the free list (no live slot still holds them) —
        evicting a share-held entry reclaims zero blocks, and a
        transient shortage would otherwise flush the whole index,
        including productive future-hit entries, without recovering any
        memory. Share-held entries are evicted only when nothing
        reclaimable remains (their references still unpin the blocks
        once the sharing slots retire, so the caller's retry loop stays
        finite)."""
        if not self._entries:
            return False
        order = sorted(range(len(self._entries)),
                       key=lambda i: self._entries[i]["used"])
        victim = next(
            (i for i in order
             if self._alloc.sole_holder(self._entries[i]["blocks"])),
            order[0])
        e = self._entries.pop(victim)
        self._alloc.free(e["blocks"])
        self.version += 1
        return True

    def clear(self) -> int:
        """Drop every entry, releasing its block references. Engine
        recovery calls this BEFORE reallocating the pool (host-side
        phase, so waiters never observe a stale index): stored entries
        would otherwise keep pointing into the fresh zeroed pool and
        silently serve all-zero KV on their next hit."""
        n = len(self._entries)
        for e in self._entries:
            self._alloc.free(e["blocks"])
        self._entries = []
        self.version += 1
        return n

    def invalidate_adapter(self, adapter: int) -> int:
        """Drop every entry stored under ``adapter`` (LoRA hot-swap:
        stored KV flowed through the OLD wk/wv)."""
        keep, dropped = [], 0
        for e in self._entries:
            if e["adapter"] == int(adapter):
                self._alloc.free(e["blocks"])
                dropped += 1
            else:
                keep.append(e)
        self._entries = keep
        if dropped:
            self.version += 1
        return dropped

    def stats(self) -> dict:
        return {"slots": self.max_entries, "entries": len(self._entries),
                "hits": self.hits, "misses": self.misses,
                "blocks_held": sum(len(e["blocks"]) for e in self._entries)}
