"""Model zoo: Llama (flagship decode path), BERT (embeddings), ViT (vision).

All models are pure-functional JAX: ``init(cfg, key) -> params`` pytrees of
plain arrays (or QuantizedLinear leaves), ``apply``-style forwards, static
shapes, layers stacked on a leading axis and iterated with ``lax.scan`` so
compile time stays flat in depth and pipeline parallelism can split the
layer axis. No torch, no module classes — params are data, which is what
``jax.sharding`` wants to see.
"""

from .common import ModelConfig, LLAMA_CONFIGS, BERT_CONFIGS, VIT_CONFIGS
from . import llama, bert, vit

__all__ = ["ModelConfig", "LLAMA_CONFIGS", "BERT_CONFIGS", "VIT_CONFIGS",
           "llama", "bert", "vit"]
