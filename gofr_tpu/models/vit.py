"""Vision Transformer (ViT-L/14 class) for batched image classification.

Serving target: Kafka -> batched ViT classification (BASELINE.md config #4).
Patchify is a reshape+matmul (not a conv) — identical math for
non-overlapping patches and a better fit for the MXU than XLA's conv path
at patch granularity.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ops.attention import full_attention
from ..ops.norms import layer_norm
from ..ops.quant import qmatmul
from .common import ModelConfig, dense_init


def n_patches(cfg: ModelConfig) -> int:
    return (cfg.image_size // cfg.patch_size) ** 2


def init(cfg: ModelConfig, key) -> dict:
    dt = cfg.jdtype
    keys = jax.random.split(key, 12)
    L, D, H, hd, F = (cfg.n_layers, cfg.dim, cfg.n_heads,
                      cfg.dim // cfg.n_heads, cfg.ffn_dim)
    patch_dim = 3 * cfg.patch_size * cfg.patch_size
    return {
        "patch_proj": dense_init(keys[0], (patch_dim, D), dt),
        "cls_token": jnp.zeros((1, 1, D), dt),
        "pos_embedding": dense_init(keys[1], (n_patches(cfg) + 1, D), dt, scale=0.02),
        "layers": {
            "norm1_w": jnp.ones((L, D), dt),
            "norm1_b": jnp.zeros((L, D), dt),
            "wq": dense_init(keys[2], (L, D, H * hd), dt),
            "wk": dense_init(keys[3], (L, D, H * hd), dt),
            "wv": dense_init(keys[4], (L, D, H * hd), dt),
            "wo": dense_init(keys[5], (L, H * hd, D), dt),
            "norm2_w": jnp.ones((L, D), dt),
            "norm2_b": jnp.zeros((L, D), dt),
            "w_in": dense_init(keys[6], (L, D, F), dt),
            "b_in": jnp.zeros((L, F), dt),
            "w_out": dense_init(keys[7], (L, F, D), dt),
            "b_out": jnp.zeros((L, D), dt),
        },
        "final_norm_w": jnp.ones((D,), dt),
        "final_norm_b": jnp.zeros((D,), dt),
        "head": dense_init(keys[8], (D, cfg.n_classes), dt),
    }


def patchify(images: jnp.ndarray, patch: int) -> jnp.ndarray:
    """[B, H, W, 3] -> [B, n_patches, 3*patch*patch]."""
    B, H, W, C = images.shape
    gh, gw = H // patch, W // patch
    x = images.reshape(B, gh, patch, gw, patch, C)
    x = x.transpose(0, 1, 3, 2, 4, 5)  # B, gh, gw, p, p, C
    return x.reshape(B, gh * gw, patch * patch * C)


def forward(params: dict, cfg: ModelConfig, images: jnp.ndarray) -> jnp.ndarray:
    """images [B, H, W, 3] float -> logits [B, n_classes] f32 (pre-LN ViT)."""
    B = images.shape[0]
    H, hd = cfg.n_heads, cfg.dim // cfg.n_heads

    x = qmatmul(patchify(images.astype(cfg.jdtype), cfg.patch_size),
                params["patch_proj"])
    cls = jnp.broadcast_to(params["cls_token"], (B, 1, cfg.dim))
    x = jnp.concatenate([cls, x], axis=1) + params["pos_embedding"][None]
    S = x.shape[1]

    def body(x, w):
        h = layer_norm(x, w["norm1_w"], w["norm1_b"], cfg.norm_eps)
        q = qmatmul(h, w["wq"]).reshape(B, S, H, hd)
        k = qmatmul(h, w["wk"]).reshape(B, S, H, hd)
        v = qmatmul(h, w["wv"]).reshape(B, S, H, hd)
        x = x + qmatmul(full_attention(q, k, v).reshape(B, S, H * hd), w["wo"])
        h = layer_norm(x, w["norm2_w"], w["norm2_b"], cfg.norm_eps)
        x = x + qmatmul(jax.nn.gelu(qmatmul(h, w["w_in"]) + w["b_in"]), w["w_out"]) + w["b_out"]
        return x, None

    x, _ = jax.lax.scan(body, x, params["layers"])
    x = layer_norm(x, params["final_norm_w"], params["final_norm_b"], cfg.norm_eps)
    return qmatmul(x[:, 0], params["head"]).astype(jnp.float32)
