"""Llama-family decoder: GQA + RoPE + SwiGLU, KV-cache prefill/decode.

TPU-first design decisions:
  - Layer weights are STACKED on a leading [L, ...] axis and iterated with
    ``lax.scan`` — one compiled layer body regardless of depth (compile time
    flat in n_layers; the scan axis is also the natural pipeline-parallel
    split).
  - The KV cache is preallocated [L, B, Smax, KV, hd] with a per-slot
    ``lengths`` cursor, so continuous batching can retire/admit sequences
    per batch slot without reshaping anything.
  - Weights may be int8 ``QuantizedLinear`` leaves (ops.quant): decode is
    HBM-bound, so int8 halves the weight traffic per step.
  - All matmuls keep [*, dim] x [dim, out] shapes large and MXU-aligned;
    softmax in f32; everything else bf16.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..ops.attention import (causal_attention, chunk_attention,
                             decode_attention_appended,
                             window_attention_appended)
from ..ops.norms import rms_norm
from ..ops.quant import qmatmul, quantize_kv
from ..ops.rope import apply_rope, rope_frequencies
from .common import ModelConfig, dense_init


_ROPE_CACHE: dict[tuple, tuple] = {}


def get_rope_tables(cfg: ModelConfig, max_seq: int):
    """Memoized (cos, sin) tables — computed once per (model, capacity).
    Callers in a serving loop should thread these through prefill/decode_step
    so un-jitted paths don't rebuild them per token."""
    scaling_key = tuple(sorted(cfg.rope_scaling.items())) if cfg.rope_scaling else None
    key = (cfg.head_dim, max_seq, cfg.rope_theta, scaling_key)
    if key not in _ROPE_CACHE:
        tables = rope_frequencies(cfg.head_dim, max_seq,
                                  cfg.rope_theta, cfg.rope_scaling)
        # Under a trace the tables are tracers — return them but never
        # memoize (a cached tracer would leak into later traces).
        if any(isinstance(t, jax.core.Tracer) for t in tables):
            return tables
        _ROPE_CACHE[key] = tables
    return _ROPE_CACHE[key]


class KVCache(NamedTuple):
    """Preallocated decode cache. ``k``/``v`` are bf16 — or int8 when the
    per-vector ``k_scale``/``v_scale`` [L, B, Smax, KV] are present (decode
    is HBM-bound on cache+weight streaming; int8 KV halves the cache half
    of that traffic — see ops.quant.quantize_kv for the fused-dequant
    scheme)."""

    k: jnp.ndarray        # [L, B, Smax, KV, hd]
    v: jnp.ndarray        # [L, B, Smax, KV, hd]
    lengths: jnp.ndarray  # [B] int32 — valid entries per slot
    k_scale: jnp.ndarray | None = None  # [L, B, Smax, KV] f32 (int8 caches)
    v_scale: jnp.ndarray | None = None

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None


def init_cache(cfg: ModelConfig, batch: int, max_seq: int | None = None,
               dtype=None) -> KVCache:
    """``dtype=jnp.int8`` allocates a quantized cache (with scale planes);
    anything else is a plain dense cache in that dtype."""
    max_seq = max_seq or cfg.max_seq
    dtype = dtype or cfg.jdtype
    shape = (cfg.n_layers, batch, max_seq, cfg.n_kv_heads, cfg.head_dim)
    quant = jnp.dtype(dtype) == jnp.int8
    return KVCache(
        k=jnp.zeros(shape, dtype),
        v=jnp.zeros(shape, dtype),
        lengths=jnp.zeros((batch,), jnp.int32),
        k_scale=jnp.zeros(shape[:-1], jnp.float32) if quant else None,
        v_scale=jnp.zeros(shape[:-1], jnp.float32) if quant else None,
    )


def init(cfg: ModelConfig, key) -> dict:
    """Random-init params; same pytree layout a checkpoint loader fills."""
    dt = cfg.jdtype
    keys = jax.random.split(key, 12)
    L, D, H, KV, hd, F, V = (cfg.n_layers, cfg.dim, cfg.n_heads,
                             cfg.n_kv_heads, cfg.head_dim, cfg.ffn_dim,
                             cfg.vocab_size)
    if cfg.n_experts > 0:
        E = cfg.n_experts
        ffn = {
            "router": dense_init(keys[9], (L, D, E), dt),
            "w_gate": dense_init(keys[5], (L, E, D, F), dt),
            "w_up": dense_init(keys[6], (L, E, D, F), dt),
            "w_down": dense_init(keys[7], (L, E, F, D), dt),
        }
    else:
        ffn = {
            "w_gate": dense_init(keys[5], (L, D, F), dt),
            "w_up": dense_init(keys[6], (L, D, F), dt),
            "w_down": dense_init(keys[7], (L, F, D), dt),
        }
    params = {
        "embedding": dense_init(keys[0], (V, D), dt, scale=0.02),
        "layers": {
            "attn_norm": jnp.ones((L, D), dt),
            "wq": dense_init(keys[1], (L, D, H * hd), dt),
            "wk": dense_init(keys[2], (L, D, KV * hd), dt),
            "wv": dense_init(keys[3], (L, D, KV * hd), dt),
            "wo": dense_init(keys[4], (L, H * hd, D), dt),
            "ffn_norm": jnp.ones((L, D), dt),
            **ffn,
        },
        "final_norm": jnp.ones((D,), dt),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = dense_init(keys[8], (D, V), dt)
    return params


LORA_TARGETS = ("wq", "wk", "wv", "wo")


def init_lora(cfg: ModelConfig, n_adapters: int, rank: int, key) -> dict:
    """Stacked multi-LoRA leaves for the attention projections: per
    target, A [L, n_adapters, in, r] (kaiming-ish) and B
    [L, n_adapters, r, out] (ZEROS — the standard LoRA init, so every
    adapter starts as an exact no-op and adapter 0 conventionally stays
    that way: the base model). Merge the returned dict into
    params["layers"]; the layer scan slices the adapter stacks alongside
    the base weights and _lora() gathers each batch row's adapter —
    multi-tenant serving over ONE shared weight stream, a few rank-r
    GEMMs per layer of extra compute."""
    dt = cfg.jdtype
    L, D, H, KV, hd = (cfg.n_layers, cfg.dim, cfg.n_heads,
                       cfg.n_kv_heads, cfg.head_dim)
    dims = {"wq": (D, H * hd), "wk": (D, KV * hd),
            "wv": (D, KV * hd), "wo": (H * hd, D)}
    keys = jax.random.split(key, len(LORA_TARGETS))
    out = {}
    for k, name in zip(keys, LORA_TARGETS):
        din, dout = dims[name]
        out[f"lora_a_{name}"] = (jax.random.normal(
            k, (L, n_adapters, din, rank)) * din ** -0.5).astype(dt)
        out[f"lora_b_{name}"] = jnp.zeros((L, n_adapters, rank, dout), dt)
    return out


def merge_lora(params: dict, cfg: ModelConfig, adapter: int) -> dict:
    """Fold ONE adapter into dense base weights (W + A_i @ B_i) and drop
    the adapter stacks — the single-tenant deployment path, and the
    oracle the multi-LoRA tests pin the gathered path against. Requires
    unquantized base weights."""
    layers = dict(params["layers"])
    for name in LORA_TARGETS:
        a = layers.pop(f"lora_a_{name}", None)
        b = layers.pop(f"lora_b_{name}", None)
        if a is None:
            continue
        delta = jnp.einsum("ldr,lro->ldo", a[:, adapter].astype(jnp.float32),
                           b[:, adapter].astype(jnp.float32))
        layers[name] = (layers[name].astype(jnp.float32)
                        + delta).astype(layers[name].dtype)
    return {**params, "layers": layers}


def _expert_mm(h, w, pattern: str, scale_expand=(None, None)):
    """Per-expert einsum that consumes int8 QuantizedLinear expert stacks
    ([E, in, out] int8 + [E, out] scale) the same way ops.quant.qmatmul
    does for dense weights: upcast in-register, scale after the
    contraction (constant over the contracted axis, so XLA keeps it
    fused — the experts are never materialized in bf16).
    ``scale_expand``: axes to insert into the [E, out] scale so it
    broadcasts against the output — (None, None) prepends two (the
    [B,S,E,out] dense-dispatch layout); for [E,C,out] grouped buffers
    pass (slice(None), None)."""
    from ..ops.quant import QuantizedLinear

    if isinstance(w, QuantizedLinear):
        y = jnp.einsum(pattern, h, w.w.astype(h.dtype),
                       preferred_element_type=jnp.float32)
        return (y * w.scale[scale_expand]).astype(h.dtype)
    return jnp.einsum(pattern, h, w)


def _route(hf, router, k: int):
    """The ONE routing definition both dispatch layouts share: f32
    softmax over expert logits, top-k selection, renormalized weights.
    hf: [T, D] flattened tokens. Returns (probs [T,E], topv, topi
    [T,k]) — any future routing change (z-loss, jitter) lands here once
    so the dense/grouped equivalence tests keep meaning something."""
    probs = jax.nn.softmax(
        jnp.einsum("td,de->te", hf, router,
                   preferred_element_type=jnp.float32), axis=-1)
    topv, topi = jax.lax.top_k(probs, k)
    topv = topv / jnp.sum(topv, axis=-1, keepdims=True)
    return probs, topv, topi


def _moe_ffn_grouped(h, layer_w, cfg: ModelConfig, valid=None):
    """Capacity-based grouped MoE dispatch — the at-scale sibling of the
    dense-dispatch path: tokens scatter into per-expert buffers
    [E, C, D] (C = capacity_factor * T * k / E), each expert runs ONE
    batched FFN over its buffer, outputs gather back and combine by the
    renormalized top-k router weights. Compute is k/E of dense dispatch;
    the price is the standard Switch/Mixtral drop rule — assignments
    past an expert's capacity contribute zero (the residual stream
    carries those tokens unchanged). All shapes static: position-in-
    buffer comes from a cumsum over one-hot assignments, over-capacity
    writes land out of range and scatter-drop."""
    import math

    B, S, D = h.shape
    T = B * S
    E, K = cfg.n_experts, cfg.experts_per_token
    # ceil, not truncate: at capacity_factor=1.0 a perfectly balanced
    # router must fit with zero drops (Switch's convention)
    cap = max(1, math.ceil(cfg.moe_capacity_factor * T * K / E))
    hf = h.reshape(T, D)

    probs, topv, topi = _route(hf, layer_w["router"], K)      # [T, ...]

    flat_e = topi.reshape(T * K)                         # assignment order:
    tok_of = jnp.repeat(jnp.arange(T), K)                # token-major, so
    onehot = jax.nn.one_hot(flat_e, E, dtype=jnp.int32)  # earlier tokens win
    if valid is not None:
        # padding/inactive tokens must not claim expert capacity (they
        # would evict REAL tokens' assignments): zero their one-hot so
        # the position cumsum skips them, and drop their writes
        vflat = valid.reshape(T)[tok_of]
        onehot = onehot * vflat[:, None].astype(onehot.dtype)
    pos = jnp.take_along_axis(jnp.cumsum(onehot, axis=0) - onehot,
                              flat_e[:, None], axis=1)[:, 0]  # [T*K]
    keep = pos < cap
    if valid is not None:
        keep = keep & vflat

    buf = jnp.zeros((E, cap, D), h.dtype)
    buf = buf.at[flat_e, jnp.where(keep, pos, cap)].set(
        hf[tok_of], mode="drop")                          # [E, C, D]

    grouped = (slice(None), None)
    gated = jax.nn.silu(_expert_mm(buf, layer_w["w_gate"], "ecd,edf->ecf",
                                   grouped)) \
        * _expert_mm(buf, layer_w["w_up"], "ecd,edf->ecf", grouped)
    out_buf = _expert_mm(gated, layer_w["w_down"], "ecf,efd->ecd", grouped)

    vals = out_buf[flat_e, jnp.where(keep, pos, 0)]       # [T*K, D]
    vals = vals * keep[:, None].astype(vals.dtype)
    out = jnp.sum(vals.reshape(T, K, D)
                  * topv.reshape(T, K, 1).astype(vals.dtype), axis=1)
    return out.reshape(B, S, D), probs.reshape(B, S, E)


def _moe_ffn(h, layer_w, cfg: ModelConfig, valid=None):
    """Mixture-of-experts SwiGLU FFN: softmax router, top-k expert
    selection with renormalized weights, dense-dispatch combine.

    Dense dispatch (every expert computes every token, combined by a
    [B,S,E] weight matrix that is zero off the top-k) keeps shapes
    static and the whole layer one fused einsum chain — XLA-friendly and
    exactly correct. It spends E/k times the FLOPs of routed dispatch,
    which is the right trade below ~8 experts per chip; set
    ``cfg.moe_capacity_factor > 0`` to switch to capacity-based grouped
    dispatch (_moe_ffn_grouped) when expert counts grow past what dense
    dispatch amortizes.

    Weights: router [D,E]; w_gate/w_up [E,D,F]; w_down [E,F,D] — dense
    or int8 QuantizedLinear stacks (TPU_QUANT=int8 quantizes experts
    per-output-channel like every other projection).
    Returns (ffn_out [B,S,D], router_probs [B,S,E] f32 — the aux
    load-balancing loss input, collected by the training path).
    """
    if cfg.moe_capacity_factor > 0:
        return _moe_ffn_grouped(h, layer_w, cfg, valid)
    B, S, D = h.shape
    probs, topv, topi = _route(h.reshape(B * S, D), layer_w["router"],
                               cfg.experts_per_token)
    probs = probs.reshape(B, S, -1)
    topv = topv.reshape(B, S, -1)
    topi = topi.reshape(B, S, -1)
    # combine weights: zero everywhere except the chosen experts
    combine = jnp.sum(
        jax.nn.one_hot(topi, cfg.n_experts, dtype=topv.dtype)
        * topv[..., None], axis=2)                             # [B,S,E]

    gated = jax.nn.silu(_expert_mm(h, layer_w["w_gate"], "bsd,edf->bsef")) \
        * _expert_mm(h, layer_w["w_up"], "bsd,edf->bsef")
    out = _expert_mm(gated, layer_w["w_down"], "bsef,efd->bsed")
    return (jnp.einsum("bsed,bse->bsd", out,
                       combine.astype(out.dtype)), probs)


def _lora(h, layer_w, name: str, adapter):
    """Per-row LoRA delta for projection ``name``: h @ A[adapter[b]] @
    B[adapter[b]] — rank-r bottleneck, a few extra GEMMs of width r per
    layer. Zero when the params carry no adapter stacks or the caller
    passed no adapter ids. Adapter 0 is the no-op base by convention
    (init_lora zeros every B matrix, the standard LoRA init)."""
    a = layer_w.get(f"lora_a_{name}")
    if a is None or adapter is None:
        return 0
    b = layer_w[f"lora_b_{name}"]
    ha = jnp.einsum("bsd,bdr->bsr", h, a[adapter].astype(h.dtype))
    return jnp.einsum("bsr,bro->bso", ha, b[adapter].astype(h.dtype))


def _layer(x, layer_w, cfg: ModelConfig, cos, sin, positions,
           kv_write, attend, valid=None, adapter=None):
    """One transformer block. ``kv_write(k_new, v_new) -> (k_all, v_all)``
    handles cache interaction; ``attend(q, k, v)`` runs attention.
    ``adapter`` [B] int32 selects each row's LoRA adapter when the
    params carry adapter stacks (multi-LoRA serving).
    Returns (x_out, (k_stored, v_stored))."""
    B, S = x.shape[0], x.shape[1]
    H, KV, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim

    h = rms_norm(x, layer_w["attn_norm"], cfg.norm_eps)
    q = (qmatmul(h, layer_w["wq"])
         + _lora(h, layer_w, "wq", adapter)).reshape(B, S, H, hd)
    k = (qmatmul(h, layer_w["wk"])
         + _lora(h, layer_w, "wk", adapter)).reshape(B, S, KV, hd)
    v = (qmatmul(h, layer_w["wv"])
         + _lora(h, layer_w, "wv", adapter)).reshape(B, S, KV, hd)
    q = apply_rope(q, cos, sin, positions)
    k = apply_rope(k, cos, sin, positions)

    k_all, v_all = kv_write(k, v)
    attn = attend(q, k_all, v_all).reshape(B, S, H * hd)
    x = x + qmatmul(attn, layer_w["wo"]) + _lora(attn, layer_w, "wo",
                                                 adapter)

    h = rms_norm(x, layer_w["ffn_norm"], cfg.norm_eps)
    router_probs = None
    if cfg.n_experts > 0:
        ffn, router_probs = _moe_ffn(h, layer_w, cfg, valid)
        x = x + ffn
    else:
        gated = jax.nn.silu(qmatmul(h, layer_w["w_gate"])) * qmatmul(h, layer_w["w_up"])
        x = x + qmatmul(gated, layer_w["w_down"])
    return x, (k_all, v_all), router_probs


def _logits(params, cfg: ModelConfig, x):
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    if cfg.tie_embeddings:
        return jnp.dot(x, params["embedding"].T,
                       preferred_element_type=jnp.float32)
    return qmatmul(x, params["lm_head"]).astype(jnp.float32)


def _causal_scan(params: dict, cfg: ModelConfig, tokens: jnp.ndarray,
                 lengths: jnp.ndarray | None, rope_max: int, rope_tables,
                 constrain, collect_kv: bool, flash: bool = False,
                 attend_override=None, collect_router: bool = False,
                 adapter=None, mesh=None):
    """Shared causal body for forward/prefill: embed, mask, scan layers.

    Returns (x [B,S,D], kv  — stacked [L,B,S,KV,hd] pair when
    ``collect_kv`` else None, lengths [B]). ``constrain`` is an optional
    activation-sharding hook (x -> x) applied to the embedded input and
    each layer output — a stable GSPMD anchor for dp/sp layouts.

    ``flash=True`` (the serving prefill paths) routes attention through
    the Pallas flash kernel when backend+shapes allow — no S² scores, the
    long-prompt/TTFT path; ops.flash falls back to the reference
    otherwise. Training keeps the jnp reference: its backward is the
    differentiation target and XLA's fusion is fine at train batch sizes.

    ``attend_override(q, k, v, lengths)``: replaces the attention
    entirely — the hook sequence-parallel training uses to route through
    ring attention (ops.ring_attention) on sp>1 meshes.
    """
    B, S = tokens.shape
    if lengths is None:
        lengths = jnp.full((B,), S, jnp.int32)
    cos, sin = rope_tables or get_rope_tables(cfg, rope_max)
    positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))
    valid = positions < lengths[:, None]
    constrain = constrain or (lambda x: x)
    # Gather the per-token rope slices ONCE, outside the layer scan, and
    # pin them to the activation layout (data, sp, None). Gathering inside
    # each layer left the [B, S, hd/2] result's sharding to the
    # partitioner, which chose a feature-dim split and paid an
    # involuntary full-remat (replicate + repartition) per step to get
    # back to the (data, sp) layout — see apply_rope.
    cos_g = constrain(cos[positions])
    sin_g = constrain(sin[positions])

    if attend_override is not None:
        def attend(q, k, v):
            return attend_override(q, k, v, lengths)
    elif flash:
        from ..ops.flash import causal_attention_auto

        def attend(q, k, v):
            return causal_attention_auto(q, k, v, lengths=lengths,
                                         mask=valid, mesh=mesh)
    else:
        def attend(q, k, v):
            return causal_attention(q, k, v, mask=valid)

    x = constrain(params["embedding"][tokens].astype(cfg.jdtype))

    def body(x, layer_w):
        x, kv, probs = _layer(x, layer_w, cfg, cos_g, sin_g, None,
                              kv_write=lambda k, v: (k, v), attend=attend,
                              valid=valid, adapter=adapter)
        # Training drops the per-layer k/v so the scan never materializes
        # the [L,B,S,KV,hd] stacks it would otherwise carry.
        return constrain(x), (kv if collect_kv else None,
                              probs if collect_router else None)

    x, (kv, router_probs) = jax.lax.scan(body, x, params["layers"])
    return x, kv, lengths, router_probs


def forward(params: dict, cfg: ModelConfig, tokens: jnp.ndarray,
            lengths: jnp.ndarray | None = None, rope_tables=None,
            constrain=None, attend_override=None,
            return_router_probs: bool = False, adapter=None):
    """Cache-free causal forward over [B, S] tokens -> [B, S, V] f32 logits.
    The training/scoring path: no KV-cache allocation or writes.
    ``attend_override``: see _causal_scan (ring attention hook).
    ``return_router_probs``: also return the per-layer MoE router
    probabilities [L, B, S, E] (the load-balancing aux-loss input);
    returns (logits, probs) — probs is None for dense models."""
    x, _, _, probs = _causal_scan(params, cfg, tokens, lengths,
                                  tokens.shape[1], rope_tables, constrain,
                                  collect_kv=False,
                                  attend_override=attend_override,
                                  collect_router=return_router_probs,
                                  adapter=adapter)
    logits = _logits(params, cfg, x)
    if return_router_probs:
        return logits, probs
    return logits


def prefill(params: dict, cfg: ModelConfig, tokens: jnp.ndarray,
            cache: KVCache, lengths: jnp.ndarray | None = None,
            rope_tables=None, flash: bool = False,
            adapter=None, mesh=None) -> tuple[jnp.ndarray, KVCache]:
    """Process prompts [B, S] (right-padded), fill the cache.

    ``lengths`` [B]: true prompt lengths (defaults to full S).
    Returns (logits [B, S, V] in f32, cache with lengths set).
    ``flash=True`` routes attention through the Pallas flash kernel;
    on sharded jits pass ``mesh`` as well so the kernel runs under
    shard_map per head/batch shard (a bare pallas_call does not
    partition under GSPMD — ops.flash picks shard_map or the jnp
    fallback from the mesh).
    """
    S = tokens.shape[1]
    x, (k_stack, v_stack), lengths, _ = _causal_scan(
        params, cfg, tokens, lengths, cache.k.shape[2], rope_tables,
        constrain=None, collect_kv=True, flash=flash, adapter=adapter,
        mesh=mesh)
    # k_stack: [L, B, S, KV, hd] -> write into the cache's first S slots
    if S > cache.k.shape[2]:
        raise ValueError(f"prompt length {S} exceeds cache capacity {cache.k.shape[2]}")
    cache = write_kv(cache, k_stack, v_stack, (0, 0, 0, 0, 0), lengths)
    return _logits(params, cfg, x), cache


def write_kv(cache: KVCache, k_stack, v_stack, index5, lengths) -> KVCache:
    """Write bf16 KV stacks [L, B', S', KV, hd] into the cache at ``index5``
    (a 5-tuple of start indices), quantizing on write for int8 caches.
    Returns the cache with ``lengths`` replaced."""
    if cache.quantized:
        qk, sk = quantize_kv(k_stack)
        qv, sv = quantize_kv(v_stack)
        return KVCache(
            k=jax.lax.dynamic_update_slice(cache.k, qk, index5),
            v=jax.lax.dynamic_update_slice(cache.v, qv, index5),
            lengths=lengths,
            k_scale=jax.lax.dynamic_update_slice(cache.k_scale, sk, index5[:-1]),
            v_scale=jax.lax.dynamic_update_slice(cache.v_scale, sv, index5[:-1]),
        )
    return KVCache(
        k=jax.lax.dynamic_update_slice(
            cache.k, k_stack.astype(cache.k.dtype), index5),
        v=jax.lax.dynamic_update_slice(
            cache.v, v_stack.astype(cache.v.dtype), index5),
        lengths=lengths)


def prefill_kv(params: dict, cfg: ModelConfig, tokens: jnp.ndarray,
               lengths: jnp.ndarray | None = None, rope_max: int | None = None,
               rope_tables=None, flash: bool = False, adapter=None,
               logit_pos: jnp.ndarray | None = None, mesh=None):
    """Causal forward returning the raw KV stacks instead of a filled cache.

    The continuous-batching serving engine prefills ONE sequence at a time
    and writes its KV into a single slot of a shared [L, B, Smax, KV, hd]
    cache; handing back (k_stack, v_stack) [L, B, S, KV, hd] lets it
    ``dynamic_update_slice`` into that slot without allocating a throwaway
    full-capacity cache per admission.

    ``logit_pos`` [B]: serving only samples ONE position per prompt —
    passing it gathers the hidden state there BEFORE lm_head, so the
    [S, V] logits (0.5 TFLOP + a quarter-GB f32 write at S=512,
    V=128k) shrink to [1, V]. The gather must precede the projection:
    the sample position is a traced scalar, so gathering after would
    still compute every row.

    Returns (logits [B, S, V] f32 — or [B, 1, V] with ``logit_pos`` —
    k_stack, v_stack, lengths [B]).
    """
    x, (k_stack, v_stack), lengths, _ = _causal_scan(
        params, cfg, tokens, lengths, rope_max or tokens.shape[1],
        rope_tables, constrain=None, collect_kv=True, flash=flash,
        adapter=adapter, mesh=mesh)
    if logit_pos is not None:
        x = jnp.take_along_axis(x, logit_pos[:, None, None]
                                .astype(jnp.int32), axis=1)  # [B, 1, D]
    return _logits(params, cfg, x), k_stack, v_stack, lengths


def prefill_chunk(params: dict, cfg: ModelConfig, tokens: jnp.ndarray,
                  cache: KVCache, start, rope_tables=None,
                  compute_logits: bool = True, adapter=None,
                  logit_pos: jnp.ndarray | None = None):
    """Process a chunk of C prompt tokens at positions [start, start+C)
    against the growing cache — the long-prompt path (chunked prefill):
    prompts of any length up to cache capacity run as a sequence of
    fixed-shape chunk calls, so XLA compiles one program per chunk size
    instead of one per prompt length.

    Same HBM discipline as decode_step: the cache is read-only inside the
    layer scan, the chunk's KV [L, B, C, KV, hd] is written afterwards by
    one dynamic_update_slice per buffer (in place on donated caches).

    ``cache.lengths`` is NOT advanced (padding inside the final chunk makes
    the true end caller-known only) — callers set lengths once after the
    last chunk. Returns (logits [B, C, V] f32 — or None when
    ``compute_logits`` is False, sparing mid-prompt chunks the lm_head
    matmul — and the cache with KV written).
    """
    B, C = tokens.shape
    cos, sin = rope_tables or get_rope_tables(cfg, cache.k.shape[2])
    positions = start + jnp.broadcast_to(jnp.arange(C, dtype=jnp.int32),
                                         (B, C))

    x = params["embedding"][tokens].astype(cfg.jdtype)

    def body(x, xs):
        layer_w, k_layer, v_layer, ks_layer, vs_layer = xs

        def attend(q, k_new, v_new):
            return chunk_attention(q, k_layer, v_layer, k_new, v_new, start,
                                   ks_layer, vs_layer)

        x, kv, _ = _layer(x, layer_w, cfg, cos, sin, positions,
                          kv_write=lambda k, v: (k, v), attend=attend,
                          adapter=adapter)
        return x, kv

    x, (k_chunk, v_chunk) = jax.lax.scan(
        body, x, (params["layers"], cache.k, cache.v,
                  cache.k_scale, cache.v_scale))
    cache = write_kv(cache, k_chunk, v_chunk, (0, 0, start, 0, 0),
                     cache.lengths)
    if not compute_logits:
        return None, cache
    if logit_pos is not None:  # sample-one-position path: see prefill_kv
        x = jnp.take_along_axis(x, logit_pos[:, None, None]
                                .astype(jnp.int32), axis=1)  # [B, 1, D]
    return _logits(params, cfg, x), cache


def verify_step(params: dict, cfg: ModelConfig, tokens: jnp.ndarray,
                cache: KVCache, rope_tables=None,
                adapter=None) -> tuple[jnp.ndarray, KVCache]:
    """Multi-token verify pass — speculative decoding's target forward.

    ``tokens`` [B, W]: column 0 is each slot's pending last sampled
    token (the one decode_step would consume), columns 1.. are draft
    continuations. ONE weight stream computes logits at every window
    position ([B, W, V] f32 — logits[:, j] predicts the token after
    consuming tokens[:, :j+1]) and writes all W KV rows at each slot's
    cursor. ``cache.lengths`` is returned UNCHANGED: acceptance — how
    far the cursor really advances — is the caller's call, and garbage
    KV past the accepted point stays invisible behind the cursor and is
    overwritten by the next window (the same cursor-visibility contract
    decode_step documents). W=1 is exactly decode_step minus sampling.

    Why this wins: decode streams the full weight set per token; a
    verify window streams it once for up to W tokens. On agreeing
    drafts (repetitive text, prompt-lookup hits) decode becomes
    bandwidth-bound on W tokens per pass instead of one.

    CAPACITY CONTRACT: callers must ensure ``lengths + W <= capacity``
    for slots whose acceptance they will honor — rows past capacity are
    scatter-dropped and must not be accepted.
    """
    cfg = multi_request_serving_config(cfg)
    B, W = tokens.shape
    cos, sin = rope_tables or get_rope_tables(cfg, cache.k.shape[2])
    positions = cache.lengths[:, None] + jnp.arange(W, dtype=jnp.int32)[None, :]
    lengths = cache.lengths

    x = params["embedding"][tokens].astype(cfg.jdtype)  # [B, W, D]

    def body(x, xs):
        layer_w, k_layer, v_layer, ks_layer, vs_layer = xs

        def attend(q, k_new, v_new):
            return window_attention_appended(q, k_layer, v_layer, k_new,
                                             v_new, lengths, ks_layer,
                                             vs_layer)

        x, kv, _ = _layer(x, layer_w, cfg, cos, sin, positions,
                          kv_write=lambda k, v: (k, v), attend=attend,
                          adapter=adapter)
        return x, kv

    x, (k_w, v_w) = jax.lax.scan(
        body, x, (params["layers"], cache.k, cache.v,
                  cache.k_scale, cache.v_scale))
    # one scatter for all layers and window rows: [L, B, W, KV, hd] ->
    # cache[:, b, lengths[b] + j] (adjacent advanced indices broadcast)
    b_idx = jnp.arange(B)[:, None]                       # [B, 1]
    if cache.quantized:
        qk, sk = quantize_kv(k_w)
        qv, sv = quantize_kv(v_w)
        new = KVCache(
            k=cache.k.at[:, b_idx, positions].set(qk, mode="drop"),
            v=cache.v.at[:, b_idx, positions].set(qv, mode="drop"),
            lengths=lengths,
            k_scale=cache.k_scale.at[:, b_idx, positions].set(sk, mode="drop"),
            v_scale=cache.v_scale.at[:, b_idx, positions].set(sv, mode="drop"))
    else:
        new = KVCache(
            k=cache.k.at[:, b_idx, positions].set(
                k_w.astype(cache.k.dtype), mode="drop"),
            v=cache.v.at[:, b_idx, positions].set(
                v_w.astype(cache.v.dtype), mode="drop"),
            lengths=lengths)
    return _logits(params, cfg, x), new


EOS_PAD = -1  # unused entries of a per-slot on-device stop set


def decode_stop_mask(tokens: jnp.ndarray, lengths: jnp.ndarray,
                     budget: jnp.ndarray, eos_ids: jnp.ndarray,
                     capacity: jnp.ndarray) -> jnp.ndarray:
    """Per-slot stop verdict for one fused-decode scan step — the
    on-device mirror of the serving engine's host retirement checks
    (EOS set membership, token budget, cache capacity), evaluated
    INSIDE the scan so a finished stream self-deactivates mid-block
    instead of burning junk slot-steps until the host reaps (at
    pipeline depth 2 that waste would be up to 2K-1 steps per stream).

    ``tokens`` [B]: the step's sampled tokens. ``lengths`` [B]: the
    post-step cursors. ``budget`` [B]: tokens the slot may still emit
    AFTER this one (the device carry of ``_Slot.remaining``).
    ``eos_ids`` [B, E]: each request's stop set, EOS_PAD-padded (token
    ids are non-negative, so the pad can never match). ``capacity``:
    the cursor bound at which the host retires (max_seq - 2 — the next
    delivered token would reach serving capacity).

    Returns bool [B]: True = this slot emitted its LAST token this step
    (the token itself is still delivered; the slot freezes from the
    next step on). Must stay exactly equivalent to the host checks in
    ``GenerationEngine._deliver`` — depth-2 token-exactness vs depth-1
    rests on the two retiring at the same position."""
    at_eos = jnp.any(tokens[:, None] == eos_ids, axis=1)
    return at_eos | (budget <= 0) | (lengths >= capacity)


def multi_request_serving_config(cfg: ModelConfig) -> ModelConfig:
    """Config for any program that batches UNRELATED requests into one
    forward — decode over the slot pool, the engine's coalesced ``score``
    batches. Grouped MoE dispatch is FORBIDDEN there: capacity claims are
    token-major across the whole batch, so request A's tokens can evict
    request B's expert assignments and B's output would depend on what A
    routed to (verified: up to 0.5 logit cross-talk at
    capacity_factor=1.0). Dense dispatch keeps every request's result
    independent of its batch-mates; per-request programs (prefill of one
    prompt, training steps) keep grouped dispatch."""
    if cfg.n_experts > 0 and cfg.moe_capacity_factor > 0:
        return cfg.with_(moe_capacity_factor=0.0)
    return cfg


def decode_step(params: dict, cfg: ModelConfig, tokens: jnp.ndarray,
                cache: KVCache, rope_tables=None, flash: bool = False,
                adapter=None, mesh=None) -> tuple[jnp.ndarray, KVCache]:
    """One decode step for tokens [B] against the cache.

    Returns (logits [B, V] f32, updated cache with lengths+1).

    ``flash=True`` routes attention through the Pallas flash-decode
    kernel (ops.flash_decode) when backend+shapes allow — the cache
    streams from HBM exactly once, int8 on the wire. On sharded jits
    pass ``mesh`` as well: the kernel then runs under shard_map per
    head/batch shard (a bare pallas_call does not partition under
    GSPMD); the jnp reference stays the default and the fallback.

    Decode is HBM-bound, so the cache is READ-ONLY inside the layer scan
    (scan ``xs`` slicing reads each layer's [B, Smax, KV, hd] in place; the
    current token's k/v ride alongside via ``decode_attention_appended``),
    and the per-layer new-token k/v — the only novel data, [L, B, KV, hd] —
    is written by ONE scatter into the donated buffers after the scan.
    Emitting updated cache slices as scan outputs instead would rewrite the
    entire cache every token and dominate the step's HBM traffic.

    CAPACITY CONTRACT: callers must ensure ``lengths < cache capacity``
    before stepping — at capacity the scatter index is out of range and the
    write is dropped (JAX scatter OOB semantics; no data-dependent errors
    are possible under jit). The serving engine retires slots before they
    hit capacity.
    """
    # slot isolation: grouped MoE dispatch would couple batch slots
    # (see multi_request_serving_config) — force dense at decode
    cfg = multi_request_serving_config(cfg)
    B = tokens.shape[0]
    cos, sin = rope_tables or get_rope_tables(cfg, cache.k.shape[2])
    positions = cache.lengths[:, None]  # [B,1] — this token's position
    lengths = cache.lengths

    x = params["embedding"][tokens[:, None]].astype(cfg.jdtype)  # [B,1,D]

    if flash:
        import functools

        from ..ops.flash_decode import decode_attention_auto
        _decode_attn = functools.partial(decode_attention_auto, mesh=mesh)
    else:
        _decode_attn = decode_attention_appended

    def body(x, xs):
        layer_w, k_layer, v_layer, ks_layer, vs_layer = xs

        def attend(q, k_new, v_new):
            return _decode_attn(q, k_layer, v_layer, k_new, v_new,
                                lengths, ks_layer, vs_layer)

        x, kv_tok, _ = _layer(x, layer_w, cfg, cos, sin, positions,
                              kv_write=lambda k, v: (k, v), attend=attend,
                              adapter=adapter)
        return x, kv_tok

    x, (k_toks, v_toks) = jax.lax.scan(
        body, x, (params["layers"], cache.k, cache.v,
                  cache.k_scale, cache.v_scale))
    # one scatter for all layers: [L, B, 1, KV, hd] -> cache[:, b, lengths[b]]
    slots = jnp.arange(B)
    k_tok, v_tok = k_toks[:, :, 0], v_toks[:, :, 0]  # [L, B, KV, hd]
    if cache.quantized:
        qk, sk = quantize_kv(k_tok)
        qv, sv = quantize_kv(v_tok)
        new = KVCache(
            k=cache.k.at[:, slots, lengths].set(qk, mode="drop"),
            v=cache.v.at[:, slots, lengths].set(qv, mode="drop"),
            lengths=lengths + 1,
            k_scale=cache.k_scale.at[:, slots, lengths].set(sk, mode="drop"),
            v_scale=cache.v_scale.at[:, slots, lengths].set(sv, mode="drop"))
    else:
        new = KVCache(
            k=cache.k.at[:, slots, lengths].set(
                k_tok.astype(cache.k.dtype), mode="drop"),
            v=cache.v.at[:, slots, lengths].set(
                v_tok.astype(cache.v.dtype), mode="drop"),
            lengths=lengths + 1)
    return _logits(params, cfg, x[:, 0]), new
