"""The App: one object running HTTP, gRPC, metrics, subscribers and CLI.

Reference: pkg/gofr/gofr.go —
  - New (gofr.go:56) / NewCMD (gofr.go:93)
  - route registration GET/POST/PUT/PATCH/DELETE (gofr.go:190-207)
  - Run (gofr.go:108-164): metrics server goroutine, default routes
    (health/alive/favicon/catch-all, gofr.go:125-141), HTTP server, gRPC
    server if a service registered (gofr.go:144-151), one goroutine per
    subscription (gofr.go:154-161)
  - auth enablers (gofr.go:268-302), AddHTTPService (gofr.go:177),
    Subscribe (gofr.go:304), SubCommand (gofr.go:223), Migrate (gofr.go:227)

Differences by design: ``run(block=False)`` + ``stop()`` exist so apps are
testable in-process (the reference blocks forever on a WaitGroup), and the
gRPC layer supports server streaming (the reference is unary-only,
grpc.go:22-26 — streaming is required for token streaming).
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Callable, Iterable

from .config import Config, EnvConfig
from .container import Container
from .context import Context
from .http.middleware import (
    apikey_auth_middleware,
    basic_auth_middleware,
    cors_middleware,
    deadline_middleware,
    drain_middleware,
    inflight_middleware,
    logging_middleware,
    metrics_middleware,
    oauth_middleware,
    slo_class_middleware,
    tenant_middleware,
    tracer_middleware,
    JWKSKeyProvider,
)
from .http.request import Request
from .http.responder import Responder, ResponseWriter
from .http.router import Router
from .http.server import HTTPServer
from .metrics import update_system_metrics
from .static import FAVICON_ICO
from .subscriber import SubscriptionManager
from .version import __version__

# Default ports (reference pkg/gofr/default.go:3-7)
DEFAULT_HTTP_PORT = 8000
DEFAULT_METRICS_PORT = 2121
DEFAULT_GRPC_PORT = 9000

HandlerFunc = Callable[[Context], Any]


class App:
    def __init__(self, config: Config | None = None, config_folder: str = "./configs"):
        self.config: Config = config if config is not None else EnvConfig(config_folder)
        # Multi-host bootstrap FIRST (reference lifecycle precedent:
        # gofr.go:108-164 owns all process-wide setup): joining the PJRT
        # distributed runtime must precede any backend use, or the TPU
        # datasource wired below would see only this host's chips.
        from .parallel.distributed import maybe_initialize

        self._distributed = maybe_initialize(self.config)
        self.container = Container(self.config)
        self.logger = self.container.logger
        if self._distributed:
            import jax

            self.logger.info({"event": "distributed runtime joined",
                              "process_id": jax.process_index(),
                              "num_processes": jax.process_count(),
                              "global_devices": jax.device_count()})

        self.router = Router()
        self._http_registered = False
        self.http_port = self.config.get_int("HTTP_PORT", DEFAULT_HTTP_PORT)
        self.metrics_port = self.config.get_int("METRICS_PORT", DEFAULT_METRICS_PORT)
        self.grpc_port = self.config.get_int("GRPC_PORT", DEFAULT_GRPC_PORT)

        self._http_server: HTTPServer | None = None
        self._metrics_server: HTTPServer | None = None
        self._grpc_server = None
        self._grpc_services: list = []
        self.subscription_manager = SubscriptionManager(self.container)
        self._cmd_routes: list[tuple] = []
        self._running = threading.Event()
        # graceful-drain readiness: flipped FIRST in stop(grace_s>0) so
        # load balancers stop routing before the engine stops serving
        self._draining = False
        self._drain_retry_after: float | None = None

        # Gateway serving role (gofr_tpu/gateway,
        # docs/advanced-guide/gateway.md): TPU_SERVING_ROLE=gateway
        # turns this App into the prefix-affinity front door over
        # TPU_GATEWAY_REPLICAS — routes registered here so user routes
        # may still be added beside them; a misconfigured replica list
        # fails construction loudly (a silently engine-less,
        # route-less "gateway" would be a misdeployed front door).
        self._gateway = None
        role = (self.config.get("TPU_SERVING_ROLE") or "").strip().lower()
        if role == "gateway":
            from .gateway import install_gateway

            self._gateway = install_gateway(self)

        # Middleware chain in reference order (http/router.go:19-24):
        # Tracer -> Logging(+recovery) -> CORS -> Metrics [-> auth];
        # the in-flight registry sits right after Tracer so /debug/requests
        # entries carry the request's trace id for its whole lifetime.
        # The drain gate runs OUTERMOST (a draining server rejects in
        # microseconds, before any span/log work); the deadline scope
        # sits inside logging so 504s are logged with their real status.
        self.router.use(drain_middleware(lambda: self._draining,
                                         lambda: self._drain_retry_after))
        self.router.use(tracer_middleware(self.container.tracer))
        self.router.use(inflight_middleware(self.container.observe.requests))
        self.router.use(logging_middleware(self.logger))
        self.router.use(deadline_middleware())
        self.router.use(slo_class_middleware())
        # tenant scope AFTER the slo scope: a tenant's registry-default
        # class must see the request's explicit X-SLO-Class first. The
        # plane resolver is lazy — the engine is wired after this chain
        # is built, and tenancy may be off entirely.
        self.router.use(tenant_middleware(
            lambda: getattr(self.container.tpu, "tenancy", None),
            header=self.config.get("TPU_TENANT_HEADER") or "X-Tenant-Id"))
        self.router.use(cors_middleware())
        self.router.use(metrics_middleware(self.container.metrics))

    # -- handler adaptation (reference handler.go:32-36) --------------------
    def _adapt(self, fn: HandlerFunc):
        def transport_handler(req: Request, w: ResponseWriter) -> None:
            ctx = Context(request=req, container=self.container, responder=Responder(w))
            with ctx.trace("gofr-handler"):
                try:
                    data = fn(ctx)
                except Exception as e:
                    Responder(w).respond(None, e)
                    if not hasattr(e, "status_code"):
                        raise  # let logging middleware record the traceback
                    return
            if w._streaming or (w.body and data is None):
                return  # handler streamed or wrote directly
            Responder(w).respond(data, None)
        transport_handler.__name__ = getattr(fn, "__name__", "handler")
        return transport_handler

    def add_route(self, method: str, path: str, fn: HandlerFunc) -> None:
        """reference gofr.go:209 add — registers and marks HTTP serving on."""
        self._http_registered = True
        self.router.add(method, path, self._adapt(fn))

    def _route_decorator(self, method: str, path: str):
        def deco(fn: HandlerFunc) -> HandlerFunc:
            self.add_route(method, path, fn)
            return fn
        return deco

    def get(self, path: str, fn: HandlerFunc | None = None):
        """``app.get("/x", handler)`` or ``@app.get("/x")`` (gofr.go:190)."""
        if fn is None:
            return self._route_decorator("GET", path)
        self.add_route("GET", path, fn)
        return fn

    def post(self, path: str, fn: HandlerFunc | None = None):
        if fn is None:
            return self._route_decorator("POST", path)
        self.add_route("POST", path, fn)
        return fn

    def put(self, path: str, fn: HandlerFunc | None = None):
        if fn is None:
            return self._route_decorator("PUT", path)
        self.add_route("PUT", path, fn)
        return fn

    def patch(self, path: str, fn: HandlerFunc | None = None):
        if fn is None:
            return self._route_decorator("PATCH", path)
        self.add_route("PATCH", path, fn)
        return fn

    def delete(self, path: str, fn: HandlerFunc | None = None):
        if fn is None:
            return self._route_decorator("DELETE", path)
        self.add_route("DELETE", path, fn)
        return fn

    # -- auth enablers (reference gofr.go:268-302) ---------------------------
    def enable_basic_auth(self, users: dict[str, str] | None = None,
                          validate: Callable[[str, str], bool] | None = None) -> None:
        self.router.use(basic_auth_middleware(users, validate))

    def enable_apikey_auth(self, *keys: str, validate: Callable[[str], bool] | None = None) -> None:
        self.router.use(apikey_auth_middleware(keys, validate))

    def enable_oauth(self, jwks_url: str, refresh_interval: float = 300.0, http_get=None) -> None:
        provider = JWKSKeyProvider(jwks_url, refresh_interval, http_get=http_get)
        self._jwks_provider = provider  # kept so stop() can halt its refresh thread
        self.router.use(oauth_middleware(provider))

    # -- services (reference gofr.go:177 AddHTTPService) ---------------------
    def add_http_service(self, name: str, address: str, *options) -> None:
        from .service import new_http_service

        self.container.register_service(
            name,
            new_http_service(address, self.logger, self.container.metrics, *options,
                             tracer=self.container.tracer),
        )

    # -- pub/sub (reference gofr.go:304-312) ---------------------------------
    def subscribe(self, topic: str, fn: HandlerFunc | None = None):
        if fn is None:
            def deco(f: HandlerFunc) -> HandlerFunc:
                self.subscription_manager.register(topic, f)
                return f
            return deco
        self.subscription_manager.register(topic, fn)
        return fn

    # -- gRPC (reference gofr.go:49-53 RegisterService) ----------------------
    def register_grpc_service(self, service) -> None:
        self._grpc_services.append(service)

    # -- CLI (reference gofr.go:223 SubCommand) ------------------------------
    def sub_command(self, pattern: str, fn: HandlerFunc | None = None, description: str = ""):
        if fn is None:
            def deco(f: HandlerFunc) -> HandlerFunc:
                self._cmd_routes.append((pattern, f, description))
                return f
            return deco
        self._cmd_routes.append((pattern, fn, description))
        return fn

    # -- migrations (reference gofr.go:227-229 Migrate) ----------------------
    def migrate(self, migrations: dict) -> None:
        from .migration import run as migration_run

        migration_run(migrations, self.container)

    # -- default routes (reference gofr.go:125-141, handler.go:38-57) --------
    def _install_default_routes(self) -> None:
        def health(req: Request, w: ResponseWriter) -> None:
            payload = self.container.health()
            w.set_header("Content-Type", "application/json")
            # the "obs" sibling makes every health poll a fleet clock
            # carrier (observe/clock.py): the send-side wall stamp is
            # the NTP sample's t1==t2, and metrics_port tells the
            # poller where this process's /debug surface lives
            w.write(json.dumps(
                {"data": payload,
                 "obs": {"wall_s": time.time(),
                         "metrics_port": self.metrics_port}},
                default=str).encode())

        def alive(req: Request, w: ResponseWriter) -> None:
            w.set_header("Content-Type", "application/json")
            w.write(b'{"data":{"status":"UP"}}')

        def favicon(req: Request, w: ResponseWriter) -> None:
            w.set_header("Content-Type", "image/x-icon")
            w.write(FAVICON_ICO)

        self.router.add("GET", "/.well-known/health", health)
        self.router.add("GET", "/.well-known/alive", alive)
        self.router.add("GET", "/favicon.ico", favicon)

    def _metrics_router(self) -> Router:
        r = Router()

        def metrics_handler(req: Request, w: ResponseWriter) -> None:
            update_system_metrics(self.container.metrics)
            # content negotiation (OpenMetrics spec): only an explicit
            # Accept for application/openmetrics-text gets the exemplar-
            # carrying OpenMetrics exposition; every other scraper keeps
            # the Prometheus 0.0.4 text format byte-identically
            accept = req.header("Accept") or ""
            if "application/openmetrics-text" in accept:
                w.set_header("Content-Type", "application/openmetrics-text; "
                                             "version=1.0.0; charset=utf-8")
                w.write(self.container.metrics.render_openmetrics().encode())
                return
            w.set_header("Content-Type", "text/plain; version=0.0.4; charset=utf-8")
            w.write(self.container.metrics.render_prometheus().encode())

        r.add("GET", "/metrics", metrics_handler)
        # /debug introspection pages live beside /metrics: same port,
        # same network policy (observe/debug.py — requests, events,
        # vars, pprof)
        from .observe.debug import install_debug_routes

        install_debug_routes(r, self)
        return r

    # -- lifecycle (reference gofr.go:108-164 Run) ---------------------------
    def run(self, block: bool = True) -> None:
        c = self.container
        self.logger.info({"event": "starting app", "name": c.app_name,
                          "version": c.app_version, "framework": __version__})

        self._metrics_server = HTTPServer(self._metrics_router(), self.metrics_port, self.logger)
        self._metrics_server.start()
        self.metrics_port = self._metrics_server.port
        # a decode worker's ingest listener advertises this process's
        # debug surface in HELLO_OK, so prefill peers learn where to
        # pull /debug/timeline + /debug/events for the fleet merge
        pd_ingest = getattr(self.container.tpu, "pd_ingest", None)
        if pd_ingest is not None:
            pd_ingest.debug_port = self.metrics_port

        if self._http_registered:
            self._install_default_routes()
            self._http_server = HTTPServer(self.router, self.http_port, self.logger)
            self._http_server.start()
            self.http_port = self._http_server.port

        if self._grpc_services:
            from .grpcx.server import GRPCServer

            self._grpc_server = GRPCServer(
                self._grpc_services, self.grpc_port, self.container)
            self._grpc_server.start()
            self.grpc_port = self._grpc_server.port

        if self.subscription_manager.subscriptions:
            self.subscription_manager.start()

        if self._gateway is not None:
            # health polling belongs to a RUNNING gateway: a merely
            # constructed App must not spawn background replica I/O
            self._gateway.table.start()

        self._running.set()
        if block:
            try:
                threading.Event().wait()
            except KeyboardInterrupt:
                self.stop()

    def stop(self, grace_s: float = 0.0) -> None:
        """Stop the app. ``grace_s > 0`` drains first, k8s-style, and the
        FIRST act of the grace window is flipping readiness: HTTP
        ``/.well-known/health`` answers 503 + Retry-After and gRPC
        health reports NOT_SERVING, so load balancers stop routing
        BEFORE the engine stops serving. New requests then get
        503/UNAVAILABLE + Retry-After while pub/sub consumption stops,
        and the TPU generation engine finishes every in-flight stream
        (up to the grace window) WITH the HTTP/gRPC listeners still up —
        clients receive complete streams over their live connections —
        then everything tears down. The reference stops its servers with
        Go's graceful http.Server.Shutdown; streaming engines need the
        readiness flip + engine-level drain on top."""
        if grace_s > 0:
            self._drain_retry_after = grace_s
            self._draining = True  # HTTP readiness: health 503, new -> 503
            if self._grpc_server is not None:
                self._grpc_server.start_draining(retry_after=grace_s)
            self.logger.info({"event": "drain started: readiness down",
                              "grace_s": grace_s})
            self.subscription_manager.stop()
            # grace_s bounds the WHOLE drain, not each phase: an
            # operator sizing a terminationGracePeriod against it must
            # not be SIGKILLed because sequential waits stacked up
            t_end = time.monotonic() + grace_s
            tpu = getattr(self.container, "tpu", None)
            gen = getattr(tpu, "generator", None)
            if gen is not None:
                drained = gen.drain(grace_s)
                self.logger.info({"event": "generation engine drained",
                                  "clean": drained})
            # in-flight HTTP requests — streaming responses included —
            # finish on their handler threads WITH the listeners still
            # up (the drain gate above already rejects new ones): the
            # second half of zero-loss rolling drain. The engine drain
            # above covers generation streams; this covers every other
            # handler — a gateway's replica relays run inside their
            # handler thread, so they drain here too.
            reg = self.container.observe.requests
            while time.monotonic() < t_end and len(reg):
                time.sleep(0.02)
            self.logger.info({"event": "http in-flight drained",
                              "remaining": len(reg)})
            if self._gateway is not None:
                self.logger.info({
                    "event": "gateway drained",
                    "clean": not any(r.inflight for r in
                                     self._gateway.table.replicas)})
        for srv in (self._http_server, self._metrics_server):
            if srv is not None:
                srv.stop()
        if self._grpc_server is not None:
            self._grpc_server.stop()
        if self._gateway is not None:
            # stop the health poller; replica clients close with the
            # container's registered services below
            self._gateway.close()
        self.subscription_manager.stop()
        provider = getattr(self, "_jwks_provider", None)
        if provider is not None:
            provider.shutdown()
        self.container.close()
        self._running.clear()

    def __enter__(self) -> "App":
        self.run(block=False)
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- CMD apps (reference gofr.go:93-110 + cmd.go:27-63) -------------------
    def run_command(self, argv: Iterable[str] | None = None) -> int:
        from .cli import run_cmd

        return run_cmd(self, argv)


def new_app(config: Config | None = None, **kw) -> App:
    """reference gofr.New (gofr.go:56)."""
    return App(config, **kw)


def new_cmd(config: Config | None = None, **kw) -> App:
    """reference gofr.NewCMD (gofr.go:93) — same App, CLI entrypoint; a
    CMD_LOGS_FILE config routes logs to a file (gofr.go:98)."""
    app = App(config, **kw)
    log_file = app.config.get("CMD_LOGS_FILE")
    if log_file:
        from .glog import new_file_logger, LogLevel

        app.container.logger = new_file_logger(
            log_file, LogLevel.parse(app.config.get("LOG_LEVEL")))
        app.logger = app.container.logger
    return app
