"""Native gRPC client: unary + server-streaming calls over one HTTP/2 conn.

The reference consumes gRPC through generated grpc-go stubs (e.g.
examples/grpc-server/main_test.go dials with grpc.Dial); this client is the
framework-side equivalent for tests and inter-service calls. One connection
multiplexes concurrent calls (odd client stream ids); a reader thread
dispatches frames to per-call queues.
"""

from __future__ import annotations

import queue
import socket
import threading
import urllib.parse

from ..errors import ConnectionLost
from . import http2 as h2
from . import service as svc
from .hpack import Decoder, Encoder


def _q_get(q: queue.Queue, timeout: float | None):
    try:
        return q.get(timeout=timeout)
    except queue.Empty:
        raise svc.GRPCError(svc.DEADLINE_EXCEEDED,
                            f"no response within {timeout}s") from None


class _Call:
    __slots__ = ("sid", "q", "headers", "trailers", "send_window", "buffer",
                 "done", "recv_debt")

    def __init__(self, sid: int, initial_window: int):
        self.sid = sid
        self.q: queue.Queue = queue.Queue()  # message bytes | GRPCError | None
        self.headers: dict[str, str] = {}
        self.trailers: dict[str, str] = {}
        self.send_window = h2.FlowWindow(initial_window)
        self.buffer = bytearray()
        self.done = threading.Event()
        self.recv_debt = 0  # bytes received since the last WINDOW_UPDATE


class GRPCChannel:
    """h2c (prior-knowledge) gRPC channel to host:port."""

    def __init__(self, host: str, port: int, connect_timeout: float = 5.0,
                 options: "h2.TransportOptions | None" = None):
        self.options = options or h2.TransportOptions()
        self.target = f"{host}:{port}"
        self.sock = socket.create_connection((host, port), connect_timeout)
        # create_connection leaves connect_timeout as the PER-READ timeout;
        # a server-stream gap longer than it (first-request compile, long
        # decode) would kill the whole channel with a reader TimeoutError.
        # Reads block indefinitely; close() wakes the reader via the
        # shutdown-then-close in FrameIO.close, and per-CALL deadlines are
        # carried by grpc-timeout, not the socket.
        self.sock.settimeout(None)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.io = h2.FrameIO(self.sock, vectored=self.options.vectored)
        self.encoder = Encoder(memo=self.options.hpack_memo)
        self.decoder = Decoder()
        self._enc_lock = threading.Lock()
        self.conn_window = h2.FlowWindow(h2.DEFAULT_WINDOW)
        self.peer_initial_window = h2.DEFAULT_WINDOW
        self._calls: dict[int, _Call] = {}
        self._lock = threading.Lock()
        self._next_sid = 1
        self._closed = False
        self._error: Exception | None = None
        self._replenisher = h2.WindowReplenisher(self.io,
                                                 self.options.lazy_window)

        self.io.send_raw(h2.CLIENT_PREFACE)
        self.io.send_frame(h2.SETTINGS, 0, 0, h2.encode_settings({
            h2.SETTINGS_HEADER_TABLE_SIZE: 4096,
            h2.SETTINGS_MAX_FRAME_SIZE: h2.DEFAULT_MAX_FRAME,
        }))
        self._reader = threading.Thread(target=self._read_loop,
                                        name="gofr-grpc-client", daemon=True)
        self._reader.start()

    # -- reader --------------------------------------------------------------
    def _read_loop(self) -> None:
        try:
            while True:
                self._dispatch(self.io.recv_frame())
        except Exception as e:  # noqa: BLE001
            self._error = e
            self._teardown()

    def _teardown(self) -> None:
        with self._lock:
            calls = list(self._calls.values())
            self._calls.clear()
            self._closed = True
        for c in calls:
            c.send_window.kill()
            c.q.put(None)
        self.conn_window.kill()

    def _dispatch(self, f: h2.Frame) -> None:
        if f.type == h2.SETTINGS:
            if not f.flags & h2.FLAG_ACK:
                settings = h2.decode_settings(f.payload)
                if h2.SETTINGS_MAX_FRAME_SIZE in settings:
                    self.io.peer_max_frame = settings[h2.SETTINGS_MAX_FRAME_SIZE]
                if h2.SETTINGS_INITIAL_WINDOW_SIZE in settings:
                    new = settings[h2.SETTINGS_INITIAL_WINDOW_SIZE]
                    delta = new - self.peer_initial_window
                    self.peer_initial_window = new
                    with self._lock:
                        for c in self._calls.values():
                            c.send_window.adjust(delta)
                if h2.SETTINGS_HEADER_TABLE_SIZE in settings:
                    with self._enc_lock:
                        self.encoder.set_max_table_size(
                            settings[h2.SETTINGS_HEADER_TABLE_SIZE])
                self.io.send_frame(h2.SETTINGS, h2.FLAG_ACK, 0)
        elif f.type == h2.HEADERS:
            self._on_headers(f)
        elif f.type == h2.DATA:
            self._on_data(f)
        elif f.type == h2.WINDOW_UPDATE:
            inc = int.from_bytes(f.payload, "big") & 0x7FFFFFFF
            if f.stream_id == 0:
                self.conn_window.credit(inc)
            else:
                call = self._calls.get(f.stream_id)
                if call is not None:
                    call.send_window.credit(inc)
        elif f.type == h2.PING:
            if not f.flags & h2.FLAG_ACK:
                self.io.send_frame(h2.PING, h2.FLAG_ACK, 0, f.payload)
        elif f.type == h2.RST_STREAM:
            call = self._pop_call(f.stream_id)
            if call is not None:
                code = int.from_bytes(f.payload[:4], "big") if f.payload else 0
                call.q.put(svc.GRPCError(svc.UNAVAILABLE,
                                         f"stream reset (http2 code {code})"))
                call.q.put(None)
        elif f.type == h2.GOAWAY:
            raise ConnectionLost("server sent GOAWAY")

    def _pop_call(self, sid: int) -> _Call | None:
        with self._lock:
            return self._calls.pop(sid, None)

    def _cancel_call(self, call: _Call) -> None:
        """Release a call the consumer abandoned (iterator dropped, timeout,
        deserialization error): RST_STREAM(CANCEL) tells the server to stop
        generating into the dead stream, and popping the entry stops it
        consuming window credit. No-op if the call already finished."""
        if self._pop_call(call.sid) is None:
            return
        try:
            self.io.send_frame(h2.RST_STREAM, 0, call.sid,
                               h2.CANCEL.to_bytes(4, "big"))
        except OSError:
            pass  # connection already gone — nothing to release
        call.done.set()

    def _on_headers(self, f: h2.Frame) -> None:
        call = self._calls.get(f.stream_id)
        block = h2.strip_padding(f)
        if not f.flags & h2.FLAG_END_HEADERS:
            # collect CONTINUATIONs inline (reader thread owns recv)
            while True:
                nxt = self.io.recv_frame()
                if nxt.type != h2.CONTINUATION or nxt.stream_id != f.stream_id:
                    raise h2.ConnectionError_(h2.PROTOCOL_ERROR,
                                              "expected CONTINUATION")
                block += nxt.payload
                if nxt.flags & h2.FLAG_END_HEADERS:
                    break
        headers = {k.decode("ascii"): v.decode("utf-8", "replace")
                   for k, v in self.decoder.decode(block)}
        if call is None:
            return
        if "grpc-status" in headers:
            call.trailers.update(headers)
        else:
            call.headers.update(headers)
        if f.flags & h2.FLAG_END_STREAM:
            self._pop_call(f.stream_id)
            self._finish_call(call)

    def _finish_call(self, call: _Call) -> None:
        status = int(call.trailers.get("grpc-status", svc.UNKNOWN))
        if status != svc.OK:
            msg = urllib.parse.unquote(call.trailers.get("grpc-message", ""))
            call.q.put(svc.GRPCError(status, msg))
        call.q.put(None)
        call.done.set()

    def _on_data(self, f: h2.Frame) -> None:
        call = self._calls.get(f.stream_id)
        if f.payload:
            self._replenisher.on_data(
                call, f.stream_id, len(f.payload),
                not f.flags & h2.FLAG_END_STREAM)
        if call is None:
            return
        call.buffer.extend(h2.strip_padding(f))
        while len(call.buffer) >= 5:
            length = int.from_bytes(call.buffer[1:5], "big")
            if len(call.buffer) < 5 + length:
                break
            call.q.put(bytes(call.buffer[5 : 5 + length]))
            del call.buffer[: 5 + length]
        if f.flags & h2.FLAG_END_STREAM:
            self._pop_call(f.stream_id)
            self._finish_call(call)

    # -- calls ---------------------------------------------------------------
    def _request_headers(self, method: str, timeout: float | None,
                         metadata=None) -> list[tuple[str, str]]:
        headers = [(":method", "POST"), (":scheme", "http"),
                   (":path", method), (":authority", self.target),
                   ("content-type", "application/grpc"),
                   ("te", "trailers")]
        if timeout is not None:
            headers.append(("grpc-timeout", f"{int(timeout * 1000)}m"))
        for k, v in (metadata or {}).items():
            headers.append((k.lower(), v))
        return headers

    def _open_call(self, method: str, timeout: float | None,
                   metadata=None) -> _Call:
        """Allocate a stream and send HEADERS (no END_STREAM): the request
        side stays open for streaming sends."""
        if self._closed:
            raise svc.GRPCError(svc.UNAVAILABLE,
                                f"channel closed: {self._error!r}")
        headers = self._request_headers(method, timeout, metadata)
        # Stream ids must reach the server strictly increasing (RFC 9113
        # §5.1.1): allocate the id and emit HEADERS under one lock so
        # concurrent calls can't reorder. DATA may interleave freely after.
        with self._lock:
            sid = self._next_sid
            self._next_sid += 2
            call = _Call(sid, self.peer_initial_window)
            self._calls[sid] = call
            with self._enc_lock:
                block = self.encoder.encode(headers)
            self.io.send_frame(h2.HEADERS, h2.FLAG_END_HEADERS, sid, block)
        return call

    def _half_close(self, call: _Call) -> None:
        """End the request side (empty DATA + END_STREAM)."""
        self.io.send_frame(h2.DATA, h2.FLAG_END_STREAM, call.sid)

    def _send_message(self, call: _Call, payload: bytes, *,
                      end: bool, timeout: float | None) -> None:
        """One gRPC length-prefixed message as flow-controlled DATA;
        ``end=True`` half-closes the request side with the final frame."""
        data = svc.grpc_frame(payload)
        view = memoryview(data)
        while view:
            want = min(len(view), self.io.peer_max_frame)
            n_stream = call.send_window.consume(want, timeout=timeout or 30.0)
            n = self.conn_window.consume(n_stream, timeout=timeout or 30.0)
            if n < n_stream:  # refund credit the connection couldn't cover
                call.send_window.credit(n_stream - n)
            last = end and n == len(view)
            self.io.send_frame(h2.DATA,
                               h2.FLAG_END_STREAM if last else 0, call.sid,
                               bytes(view[:n]))
            view = view[n:]

    def _start_call(self, method: str, payload: bytes,
                    timeout: float | None, metadata=None) -> _Call:
        """Open a one-message request (unary / server-stream): on the
        fast path the WHOLE request — HEADERS + DATA + END_STREAM —
        leaves in ONE vectored write (one syscall, one packet, one
        server-reader wakeup) instead of three back-to-back. Falls back
        to open+send when the message needs multiple frames or the
        windows lack instant credit."""
        data = svc.grpc_frame(payload)
        if (self.options.vectored and not self._closed
                and len(data) <= self.io.peer_max_frame
                and self.conn_window.try_consume(len(data))):
            headers = self._request_headers(method, timeout, metadata)
            with self._lock:
                sid = self._next_sid
                self._next_sid += 2
                call = _Call(sid, self.peer_initial_window)
                if not call.send_window.try_consume(len(data)):
                    # a tiny INITIAL_WINDOW_SIZE: refund and fall back
                    self.conn_window.credit(len(data))
                else:
                    self._calls[sid] = call
                    with self._enc_lock:
                        block = self.encoder.encode(headers)
                    self.io.send_frames([
                        (h2.HEADERS, h2.FLAG_END_HEADERS, sid, block),
                        (h2.DATA, h2.FLAG_END_STREAM, sid, data)])
                    return call
        call = self._open_call(method, timeout, metadata)
        self._send_message(call, payload, end=True, timeout=timeout)
        return call

    def unary(self, method: str, request, *, codec=None, response_codec=None,
              timeout: float | None = 30.0, metadata=None):
        """Call /pkg.Service/Method; JSON codec unless codecs given."""
        codec = codec or svc.JSONCodec()
        response_codec = response_codec or codec
        call = self._start_call(method, codec.serialize(request), timeout,
                                metadata)
        try:
            msg = _q_get(call.q, timeout)
            if isinstance(msg, svc.GRPCError):
                raise msg
            if msg is None:
                raise svc.GRPCError(svc.UNAVAILABLE,
                                    f"connection lost: {self._error!r}")
            # drain trailers sentinel
            tail = _q_get(call.q, timeout)
            if isinstance(tail, svc.GRPCError):
                raise tail
            return response_codec.deserialize(msg)
        finally:
            self._cancel_call(call)  # no-op unless the call is still open

    def server_stream(self, method: str, request, *, codec=None,
                      response_codec=None, timeout: float | None = 60.0,
                      metadata=None):
        """Iterate streamed responses for /pkg.Service/Method."""
        codec = codec or svc.JSONCodec()
        response_codec = response_codec or codec
        call = self._start_call(method, codec.serialize(request), timeout,
                                metadata)
        try:
            while True:
                msg = _q_get(call.q, timeout)
                if isinstance(msg, svc.GRPCError):
                    raise msg
                if msg is None:
                    if not call.done.is_set() and self._error is not None:
                        raise svc.GRPCError(svc.UNAVAILABLE,
                                            f"connection lost: {self._error!r}")
                    return
                yield response_codec.deserialize(msg)
        finally:
            # GeneratorExit (consumer stopped iterating), _q_get timeout, or
            # any downstream error: cancel so the server releases its slot
            self._cancel_call(call)

    def client_stream(self, method: str, requests, *, codec=None,
                      response_codec=None, timeout: float | None = 30.0,
                      metadata=None):
        """Stream ``requests`` (an iterable) in, receive ONE response."""
        codec = codec or svc.JSONCodec()
        response_codec = response_codec or codec
        call = self._open_call(method, timeout, metadata)
        try:
            for r in requests:
                self._send_message(call, codec.serialize(r), end=False,
                                   timeout=timeout)
            self._half_close(call)
            msg = _q_get(call.q, timeout)
            if isinstance(msg, svc.GRPCError):
                raise msg
            if msg is None:
                raise svc.GRPCError(svc.UNAVAILABLE,
                                    f"connection lost: {self._error!r}")
            tail = _q_get(call.q, timeout)
            if isinstance(tail, svc.GRPCError):
                raise tail
            return response_codec.deserialize(msg)
        finally:
            self._cancel_call(call)  # no-op unless the call is still open

    def bidi_stream(self, method: str, *, codec=None, response_codec=None,
                    timeout: float | None = 60.0, metadata=None) -> "BidiCall":
        """Open a bidirectional stream: returns a handle with ``send()``,
        ``close_send()``, iteration over responses, and ``cancel()`` —
        requests and responses interleave freely (incremental prompts in,
        tokens out, mid-stream cancel)."""
        codec = codec or svc.JSONCodec()
        response_codec = response_codec or codec
        call = self._open_call(method, timeout, metadata)
        return BidiCall(self, call, codec, response_codec, timeout)

    def close(self) -> None:
        # _closed is written under _lock everywhere else (_teardown);
        # an unlocked flip here can interleave with a streamer checking
        # it mid-open. io.close() stays outside: it wakes the read loop,
        # whose _teardown needs the lock.
        with self._lock:
            self._closed = True
        self.io.close()


class BidiCall:
    """Client handle for one bidi RPC. Thread-safe for one sender + one
    receiver; dropping the response iterator (or ``cancel()``) sends
    RST_STREAM so the server releases whatever the stream holds."""

    def __init__(self, channel: GRPCChannel, call: _Call, codec,
                 response_codec, timeout: float | None):
        self._channel = channel
        self._call = call
        self._codec = codec
        self._response_codec = response_codec
        self._timeout = timeout
        self._send_closed = False

    def send(self, msg) -> None:
        if self._send_closed:
            raise svc.GRPCError(svc.INTERNAL, "send side already closed")
        self._channel._send_message(self._call, self._codec.serialize(msg),
                                    end=False, timeout=self._timeout)

    def close_send(self) -> None:
        """Half-close: no more requests; responses keep flowing."""
        if not self._send_closed:
            self._send_closed = True
            self._channel._half_close(self._call)

    def cancel(self) -> None:
        self._channel._cancel_call(self._call)

    def __iter__(self):
        try:
            while True:
                msg = _q_get(self._call.q, self._timeout)
                if isinstance(msg, svc.GRPCError):
                    raise msg
                if msg is None:
                    if (not self._call.done.is_set()
                            and self._channel._error is not None):
                        raise svc.GRPCError(
                            svc.UNAVAILABLE,
                            f"connection lost: {self._channel._error!r}")
                    return
                yield self._response_codec.deserialize(msg)
        finally:
            self._channel._cancel_call(self._call)


def dial(address: str, **kw) -> GRPCChannel:
    """address "host:port" -> channel (the grpc.Dial shape)."""
    host, _, port = address.partition(":")
    return GRPCChannel(host or "127.0.0.1", int(port), **kw)
