"""HTTP/2 (RFC 9113) framing: the wire layer under the native gRPC transport.

The reference gets this from grpc-go (SURVEY §2 #13 — google.golang.org/grpc
on GRPC_PORT); this framework owns its wire layer. Blocking sockets with a
thread per connection and a thread per stream — the Python mirror of
goroutine-per-stream — with writes serialized through one lock and both
levels of flow control (connection + stream send windows, §5.2) enforced.

Scope: server + client framing for gRPC's HTTP/2 profile — no push,
no priority scheduling (PRIORITY frames are parsed and ignored), TLS-free
prior-knowledge connections (h2c), as used for in-cluster gRPC.
"""

from __future__ import annotations

import socket
import struct
import threading

# frame types (§6)
DATA = 0x0
HEADERS = 0x1
PRIORITY = 0x2
RST_STREAM = 0x3
SETTINGS = 0x4
PUSH_PROMISE = 0x5
PING = 0x6
GOAWAY = 0x7
WINDOW_UPDATE = 0x8
CONTINUATION = 0x9

# flags
FLAG_END_STREAM = 0x1   # DATA, HEADERS
FLAG_ACK = 0x1          # SETTINGS, PING
FLAG_END_HEADERS = 0x4  # HEADERS, CONTINUATION
FLAG_PADDED = 0x8       # DATA, HEADERS
FLAG_PRIORITY = 0x20    # HEADERS

# settings ids (§6.5.2)
SETTINGS_HEADER_TABLE_SIZE = 0x1
SETTINGS_ENABLE_PUSH = 0x2
SETTINGS_MAX_CONCURRENT_STREAMS = 0x3
SETTINGS_INITIAL_WINDOW_SIZE = 0x4
SETTINGS_MAX_FRAME_SIZE = 0x5
SETTINGS_MAX_HEADER_LIST_SIZE = 0x6

# error codes (§7)
NO_ERROR = 0x0
PROTOCOL_ERROR = 0x1
INTERNAL_ERROR = 0x2
FLOW_CONTROL_ERROR = 0x3
STREAM_CLOSED = 0x5
FRAME_SIZE_ERROR = 0x6
REFUSED_STREAM = 0x7
CANCEL = 0x8

CLIENT_PREFACE = b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n"
DEFAULT_WINDOW = 65535
DEFAULT_MAX_FRAME = 16384
MAX_WINDOW = (1 << 31) - 1


class ConnectionError_(Exception):
    """Fatal connection-level error (mapped to GOAWAY)."""

    def __init__(self, code: int, msg: str = ""):
        super().__init__(msg or f"http2 connection error {code}")
        self.code = code


class StreamError(Exception):
    """Stream-level error (mapped to RST_STREAM)."""

    def __init__(self, stream_id: int, code: int, msg: str = ""):
        super().__init__(msg or f"http2 stream {stream_id} error {code}")
        self.stream_id = stream_id
        self.code = code


class Frame:
    __slots__ = ("type", "flags", "stream_id", "payload")

    def __init__(self, type_: int, flags: int, stream_id: int, payload: bytes):
        self.type = type_
        self.flags = flags
        self.stream_id = stream_id
        self.payload = payload

    def __repr__(self):  # pragma: no cover - debug aid
        names = {0: "DATA", 1: "HEADERS", 2: "PRIORITY", 3: "RST_STREAM",
                 4: "SETTINGS", 5: "PUSH_PROMISE", 6: "PING", 7: "GOAWAY",
                 8: "WINDOW_UPDATE", 9: "CONTINUATION"}
        return (f"<{names.get(self.type, self.type)} flags={self.flags:#x} "
                f"sid={self.stream_id} len={len(self.payload)}>")


def encode_settings(settings: dict[int, int]) -> bytes:
    return b"".join(struct.pack(">HI", k, v) for k, v in settings.items())


def decode_settings(payload: bytes) -> dict[int, int]:
    if len(payload) % 6:
        raise ConnectionError_(FRAME_SIZE_ERROR, "bad SETTINGS length")
    out = {}
    for off in range(0, len(payload), 6):
        k, v = struct.unpack_from(">HI", payload, off)
        out[k] = v
    return out


class FrameIO:
    """Thread-safe framed socket: one reader thread, many writer threads."""

    def __init__(self, sock: socket.socket, max_frame: int = DEFAULT_MAX_FRAME):
        self.sock = sock
        self.max_frame = max_frame          # what we accept (our SETTINGS)
        self.peer_max_frame = DEFAULT_MAX_FRAME  # what the peer accepts
        self._rbuf = b""
        self._wlock = threading.Lock()
        self._closed = False

    # -- reads (single reader thread) ----------------------------------------
    def _read_exact(self, n: int) -> bytes:
        while len(self._rbuf) < n:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise EOFError("peer closed connection")
            self._rbuf += chunk
        out, self._rbuf = self._rbuf[:n], self._rbuf[n:]
        return out

    def read_preface(self) -> None:
        got = self._read_exact(len(CLIENT_PREFACE))
        if got != CLIENT_PREFACE:
            raise ConnectionError_(PROTOCOL_ERROR, "bad client preface")

    def recv_frame(self) -> Frame:
        head = self._read_exact(9)
        length = int.from_bytes(head[:3], "big")
        type_, flags = head[3], head[4]
        stream_id = int.from_bytes(head[5:9], "big") & 0x7FFFFFFF
        if length > self.max_frame:
            raise ConnectionError_(FRAME_SIZE_ERROR,
                                   f"frame of {length} bytes exceeds {self.max_frame}")
        payload = self._read_exact(length) if length else b""
        return Frame(type_, flags, stream_id, payload)

    # -- writes (any thread) -------------------------------------------------
    def send_frame(self, type_: int, flags: int, stream_id: int,
                   payload: bytes = b"") -> None:
        self.send_frames([(type_, flags, stream_id, payload)])

    def send_frames(self, frames) -> None:
        """Write one or more frames in ONE sendall — the first-token
        fast path coalesces the response HEADERS and the first DATA
        frame so a streaming client sees one packet (one syscall, one
        wakeup) instead of two back-to-back."""
        buf = bytearray()
        for type_, flags, stream_id, payload in frames:
            if len(payload) > self.peer_max_frame:
                raise ConnectionError_(FRAME_SIZE_ERROR,
                                       "frame too large for peer")
            buf += (len(payload).to_bytes(3, "big") + bytes((type_, flags))
                    + stream_id.to_bytes(4, "big") + payload)
        with self._wlock:
            if self._closed:
                raise EOFError("connection closed")
            self.sock.sendall(buf)

    def close(self) -> None:
        with self._wlock:
            self._closed = True
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self.sock.close()


class FlowWindow:
    """A send window: block until credit, credit on WINDOW_UPDATE (§5.2)."""

    def __init__(self, initial: int = DEFAULT_WINDOW):
        self.value = initial
        self._cond = threading.Condition()
        self._dead = False

    def consume(self, want: int, timeout: float | None = None) -> int:
        """Block until some credit exists; returns min(want, credit)."""
        with self._cond:
            while self.value <= 0 and not self._dead:
                if not self._cond.wait(timeout):
                    raise TimeoutError("flow-control window starved")
            if self._dead:
                raise EOFError("stream/connection closed")
            take = min(want, self.value)
            self.value -= take
            return take

    def credit(self, n: int) -> None:
        with self._cond:
            self.value += n
            if self.value > MAX_WINDOW:
                raise ConnectionError_(FLOW_CONTROL_ERROR, "window overflow")
            self._cond.notify_all()

    def adjust(self, delta: int) -> None:
        """INITIAL_WINDOW_SIZE change retro-adjusts open streams (§6.9.2)."""
        with self._cond:
            self.value += delta
            self._cond.notify_all()

    def kill(self) -> None:
        with self._cond:
            self._dead = True
            self._cond.notify_all()


def strip_padding(frame: Frame) -> bytes:
    """Remove PADDED/PRIORITY decorations from HEADERS/DATA payloads."""
    data = frame.payload
    if frame.flags & FLAG_PADDED:
        if not data:
            raise ConnectionError_(PROTOCOL_ERROR, "padded frame w/o pad length")
        pad = data[0]
        data = data[1:]
        if pad > len(data):
            raise ConnectionError_(PROTOCOL_ERROR, "padding exceeds payload")
        data = data[: len(data) - pad]
    if frame.type == HEADERS and frame.flags & FLAG_PRIORITY:
        if len(data) < 5:
            raise ConnectionError_(PROTOCOL_ERROR, "short priority block")
        data = data[5:]
    return data
