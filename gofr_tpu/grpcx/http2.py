"""HTTP/2 (RFC 9113) framing: the wire layer under the native gRPC transport.

The reference gets this from grpc-go (SURVEY §2 #13 — google.golang.org/grpc
on GRPC_PORT); this framework owns its wire layer. Blocking sockets with a
thread per connection and a thread per stream — the Python mirror of
goroutine-per-stream — with writes serialized through one lock and both
levels of flow control (connection + stream send windows, §5.2) enforced.

Scope: server + client framing for gRPC's HTTP/2 profile — no push,
no priority scheduling (PRIORITY frames are parsed and ignored), TLS-free
prior-knowledge connections (h2c), as used for in-cluster gRPC.
"""

from __future__ import annotations

import socket
import struct
import threading

from ..errors import ConnectionLost
from ..wire import SocketWriter

# frame types (§6)
DATA = 0x0
HEADERS = 0x1
PRIORITY = 0x2
RST_STREAM = 0x3
SETTINGS = 0x4
PUSH_PROMISE = 0x5
PING = 0x6
GOAWAY = 0x7
WINDOW_UPDATE = 0x8
CONTINUATION = 0x9

# flags
FLAG_END_STREAM = 0x1   # DATA, HEADERS
FLAG_ACK = 0x1          # SETTINGS, PING
FLAG_END_HEADERS = 0x4  # HEADERS, CONTINUATION
FLAG_PADDED = 0x8       # DATA, HEADERS
FLAG_PRIORITY = 0x20    # HEADERS

# settings ids (§6.5.2)
SETTINGS_HEADER_TABLE_SIZE = 0x1
SETTINGS_ENABLE_PUSH = 0x2
SETTINGS_MAX_CONCURRENT_STREAMS = 0x3
SETTINGS_INITIAL_WINDOW_SIZE = 0x4
SETTINGS_MAX_FRAME_SIZE = 0x5
SETTINGS_MAX_HEADER_LIST_SIZE = 0x6

# error codes (§7)
NO_ERROR = 0x0
PROTOCOL_ERROR = 0x1
INTERNAL_ERROR = 0x2
FLOW_CONTROL_ERROR = 0x3
STREAM_CLOSED = 0x5
FRAME_SIZE_ERROR = 0x6
REFUSED_STREAM = 0x7
CANCEL = 0x8

CLIENT_PREFACE = b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n"
DEFAULT_WINDOW = 65535
DEFAULT_MAX_FRAME = 16384
MAX_WINDOW = (1 << 31) - 1

# lazy receive-window replenish threshold: WINDOW_UPDATEs batch until a
# quarter of the default window is consumed instead of going out per
# DATA frame — the dominant per-token syscall on the streaming path
WINDOW_REPLENISH = DEFAULT_WINDOW // 4


class TransportOptions:
    """Feature switches for the transport fast path.

    The default construction enables everything; ``legacy()`` pins the
    pre-fast-path wire behavior and is the "before" arm measured by
    tools/transport_bench.py (and the fallback if a fast-path lever
    ever needs to be ruled out in production).

      hpack_memo    — encode caches + pre-encoded stateless server
                      blocks (hpack.encode_stateless)
      vectored      — sendmsg frame writes with nonblocking backlog
                      (wire.SocketWriter fast path)
      lazy_window   — batch WINDOW_UPDATE replenish at WINDOW_REPLENISH
                      instead of two eager frames per DATA frame
      zero_handoff  — deliver server-stream messages on the producing
                      thread (ServerStream + GenStream sink); effective
                      only with ``vectored`` on, because the sink's
                      writes must be nonblocking — the server ignores it
                      otherwise
    """

    __slots__ = ("hpack_memo", "vectored", "lazy_window", "zero_handoff")

    def __init__(self, hpack_memo: bool = True, vectored: bool = True,
                 lazy_window: bool = True, zero_handoff: bool = True):
        self.hpack_memo = hpack_memo
        self.vectored = vectored
        self.lazy_window = lazy_window
        self.zero_handoff = zero_handoff

    @classmethod
    def legacy(cls) -> "TransportOptions":
        return cls(hpack_memo=False, vectored=False, lazy_window=False,
                   zero_handoff=False)


class ConnectionError_(Exception):
    """Fatal connection-level error (mapped to GOAWAY)."""

    def __init__(self, code: int, msg: str = ""):
        super().__init__(msg or f"http2 connection error {code}")
        self.code = code


class StreamError(Exception):
    """Stream-level error (mapped to RST_STREAM)."""

    def __init__(self, stream_id: int, code: int, msg: str = ""):
        super().__init__(msg or f"http2 stream {stream_id} error {code}")
        self.stream_id = stream_id
        self.code = code


class Frame:
    __slots__ = ("type", "flags", "stream_id", "payload")

    def __init__(self, type_: int, flags: int, stream_id: int, payload: bytes):
        self.type = type_
        self.flags = flags
        self.stream_id = stream_id
        self.payload = payload

    def __repr__(self):  # pragma: no cover - debug aid
        names = {0: "DATA", 1: "HEADERS", 2: "PRIORITY", 3: "RST_STREAM",
                 4: "SETTINGS", 5: "PUSH_PROMISE", 6: "PING", 7: "GOAWAY",
                 8: "WINDOW_UPDATE", 9: "CONTINUATION"}
        return (f"<{names.get(self.type, self.type)} flags={self.flags:#x} "
                f"sid={self.stream_id} len={len(self.payload)}>")


def encode_settings(settings: dict[int, int]) -> bytes:
    return b"".join(struct.pack(">HI", k, v) for k, v in settings.items())


def decode_settings(payload: bytes) -> dict[int, int]:
    if len(payload) % 6:
        raise ConnectionError_(FRAME_SIZE_ERROR, "bad SETTINGS length")
    out = {}
    for off in range(0, len(payload), 6):
        k, v = struct.unpack_from(">HI", payload, off)
        out[k] = v
    return out


class FrameIO:
    """Thread-safe framed socket: one reader thread, many writer threads.

    Writes go through a wire.SocketWriter: one vectored syscall carries
    any number of frames, and ``block=False`` sends never stall the
    caller (bytes park in the writer's ordered backlog under contention
    or a full socket buffer — the zero-handoff delivery path relies on
    this). ``vectored=False`` pins the legacy one-sendall-per-call
    behavior for A/B measurement."""

    def __init__(self, sock: socket.socket, max_frame: int = DEFAULT_MAX_FRAME,
                 vectored: bool = True):
        self.sock = sock
        self.max_frame = max_frame          # what we accept (our SETTINGS)
        self.peer_max_frame = DEFAULT_MAX_FRAME  # what the peer accepts
        self._rbuf = b""
        self.writer = SocketWriter(sock)
        self.vectored = vectored
        self.frames_sent = 0
        self.coalesced_header_data = 0  # writes carrying HEADERS+DATA together

    # -- reads (single reader thread) ----------------------------------------
    def _read_exact(self, n: int) -> bytes:
        while len(self._rbuf) < n:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise ConnectionLost("peer closed connection")
            self._rbuf += chunk
        out, self._rbuf = self._rbuf[:n], self._rbuf[n:]
        return out

    def read_preface(self) -> None:
        got = self._read_exact(len(CLIENT_PREFACE))
        if got != CLIENT_PREFACE:
            raise ConnectionError_(PROTOCOL_ERROR, "bad client preface")

    def recv_frame(self) -> Frame:
        head = self._read_exact(9)
        length = int.from_bytes(head[:3], "big")
        type_, flags = head[3], head[4]
        stream_id = int.from_bytes(head[5:9], "big") & 0x7FFFFFFF
        if length > self.max_frame:
            raise ConnectionError_(FRAME_SIZE_ERROR,
                                   f"frame of {length} bytes exceeds {self.max_frame}")
        payload = self._read_exact(length) if length else b""
        return Frame(type_, flags, stream_id, payload)

    # -- writes (any thread) -------------------------------------------------
    def send_frame(self, type_: int, flags: int, stream_id: int,
                   payload: bytes = b"") -> None:
        self.send_frames([(type_, flags, stream_id, payload)])

    def send_frames(self, frames, block: bool = True) -> bool:
        """Write one or more frames in ONE vectored write — the
        first-token fast path coalesces the response HEADERS and the
        first DATA frame so a streaming client sees one packet (one
        syscall, one wakeup) instead of two back-to-back; fused decode
        blocks batch their DATA frames the same way. ``block=False``
        commits the bytes without ever stalling the caller (see
        SocketWriter); returns False when they were parked in the
        backlog, in which case the caller must arrange a later flush."""
        bufs = []
        saw_headers = False
        for type_, flags, stream_id, payload in frames:
            if len(payload) > self.peer_max_frame:
                raise ConnectionError_(FRAME_SIZE_ERROR,
                                       "frame too large for peer")
            bufs.append(len(payload).to_bytes(3, "big") + bytes((type_, flags))
                        + stream_id.to_bytes(4, "big"))
            if payload:
                bufs.append(payload)
            if type_ == HEADERS:
                saw_headers = True
            elif type_ == DATA and saw_headers:
                self.coalesced_header_data += 1
                saw_headers = False
        self.frames_sent += len(frames)
        if self.vectored:
            return self.writer.write(bufs, block=block)
        # legacy wire path: one joined sendall per call, always
        # blocking (the pre-fast-path behavior, kept for A/B)
        return self.writer.write(b"".join(bufs), block=True)

    def send_raw(self, data: bytes) -> None:
        """Raw blocking write outside the framing (client preface)."""
        self.writer.write(data, block=True)

    def flush(self) -> None:
        self.writer.flush()

    def close(self) -> None:
        self.writer.close()


class WindowReplenisher:
    """Receive-window replenish policy, shared by the server connection
    and the client channel so the two sides of the own wire can never
    drift apart.

    The fast path batches debt until WINDOW_REPLENISH and ships the
    connection + stream updates in ONE write — the eager policy cost
    two syscalls per received DATA frame, the dominant per-token
    syscall on a streaming path. ``holder`` is the per-stream state
    carrying ``recv_debt`` (server ``_Stream`` / client ``_Call``), or
    None when the stream is already gone (connection-level accounting
    still applies)."""

    __slots__ = ("io", "lazy", "_debt")

    def __init__(self, io: "FrameIO", lazy: bool):
        self.io = io
        self.lazy = lazy
        self._debt = 0  # connection-level consumed-but-unannounced bytes

    def on_data(self, holder, sid: int, n: int, stream_open: bool) -> None:
        if not self.lazy:
            packed = struct.pack(">I", n)
            self.io.send_frame(WINDOW_UPDATE, 0, 0, packed)
            if holder is not None and stream_open:
                self.io.send_frame(WINDOW_UPDATE, 0, sid, packed)
            return
        ups = []
        self._debt += n
        if self._debt >= WINDOW_REPLENISH:
            ups.append((WINDOW_UPDATE, 0, 0, struct.pack(">I", self._debt)))
            self._debt = 0
        if holder is not None and stream_open:
            holder.recv_debt += n
            if holder.recv_debt >= WINDOW_REPLENISH:
                ups.append((WINDOW_UPDATE, 0, sid,
                            struct.pack(">I", holder.recv_debt)))
                holder.recv_debt = 0
        if ups:
            self.io.send_frames(ups)


class FlowWindow:
    """A send window: block until credit, credit on WINDOW_UPDATE (§5.2)."""

    def __init__(self, initial: int = DEFAULT_WINDOW):
        self.value = initial
        self._cond = threading.Condition()
        self._dead = False

    def consume(self, want: int, timeout: float | None = None) -> int:
        """Block until some credit exists; returns min(want, credit)."""
        with self._cond:
            while self.value <= 0 and not self._dead:
                if not self._cond.wait(timeout):
                    raise TimeoutError("flow-control window starved")
            if self._dead:
                raise ConnectionLost("stream/connection closed")
            take = min(want, self.value)
            self.value -= take
            return take

    def try_consume(self, want: int) -> bool:
        """All-or-nothing nonblocking claim — the zero-handoff fast path
        takes a whole message's credit or falls back to the worker
        thread (which can afford to block in ``consume``)."""
        with self._cond:
            if self._dead or self.value < want:
                return False
            self.value -= want
            return True

    def credit(self, n: int) -> None:
        with self._cond:
            self.value += n
            if self.value > MAX_WINDOW:
                raise ConnectionError_(FLOW_CONTROL_ERROR, "window overflow")
            self._cond.notify_all()

    def adjust(self, delta: int) -> None:
        """INITIAL_WINDOW_SIZE change retro-adjusts open streams (§6.9.2)."""
        with self._cond:
            self.value += delta
            self._cond.notify_all()

    def kill(self) -> None:
        with self._cond:
            self._dead = True
            self._cond.notify_all()


def strip_padding(frame: Frame) -> bytes:
    """Remove PADDED/PRIORITY decorations from HEADERS/DATA payloads."""
    data = frame.payload
    if frame.flags & FLAG_PADDED:
        if not data:
            raise ConnectionError_(PROTOCOL_ERROR, "padded frame w/o pad length")
        pad = data[0]
        data = data[1:]
        if pad > len(data):
            raise ConnectionError_(PROTOCOL_ERROR, "padding exceeds payload")
        data = data[: len(data) - pad]
    if frame.type == HEADERS and frame.flags & FLAG_PRIORITY:
        if len(data) < 5:
            raise ConnectionError_(PROTOCOL_ERROR, "short priority block")
        data = data[5:]
    return data
