"""HPACK (RFC 7541) header compression for the native gRPC transport.

Written from the spec: integer/string primitives (§5), indexed and literal
field representations (§6), the 61-entry static table (Appendix A) and the
Huffman code (Appendix B, data in ``_hufftable``). The reference framework
gets HTTP/2 for free from grpc-go (SURVEY §2 #13); this framework carries
its own wire layer, so compression lives here.

Both peers of this implementation interoperate with any RFC-conformant
HPACK (dynamic-table size updates honored, Huffman both directions).
"""

from __future__ import annotations

from ._hufftable import HUFFMAN_CODES

STATIC_TABLE: tuple[tuple[bytes, bytes], ...] = (
    (b":authority", b""),
    (b":method", b"GET"),
    (b":method", b"POST"),
    (b":path", b"/"),
    (b":path", b"/index.html"),
    (b":scheme", b"http"),
    (b":scheme", b"https"),
    (b":status", b"200"),
    (b":status", b"204"),
    (b":status", b"206"),
    (b":status", b"304"),
    (b":status", b"400"),
    (b":status", b"404"),
    (b":status", b"500"),
    (b"accept-charset", b""),
    (b"accept-encoding", b"gzip, deflate"),
    (b"accept-language", b""),
    (b"accept-ranges", b""),
    (b"accept", b""),
    (b"access-control-allow-origin", b""),
    (b"age", b""),
    (b"allow", b""),
    (b"authorization", b""),
    (b"cache-control", b""),
    (b"content-disposition", b""),
    (b"content-encoding", b""),
    (b"content-language", b""),
    (b"content-length", b""),
    (b"content-location", b""),
    (b"content-range", b""),
    (b"content-type", b""),
    (b"cookie", b""),
    (b"date", b""),
    (b"etag", b""),
    (b"expect", b""),
    (b"expires", b""),
    (b"from", b""),
    (b"host", b""),
    (b"if-match", b""),
    (b"if-modified-since", b""),
    (b"if-none-match", b""),
    (b"if-range", b""),
    (b"if-unmodified-since", b""),
    (b"last-modified", b""),
    (b"link", b""),
    (b"location", b""),
    (b"max-forwards", b""),
    (b"proxy-authenticate", b""),
    (b"proxy-authorization", b""),
    (b"range", b""),
    (b"referer", b""),
    (b"refresh", b""),
    (b"retry-after", b""),
    (b"server", b""),
    (b"set-cookie", b""),
    (b"strict-transport-security", b""),
    (b"transfer-encoding", b""),
    (b"user-agent", b""),
    (b"vary", b""),
    (b"via", b""),
    (b"www-authenticate", b""),
)

_STATIC_FULL = {entry: i + 1 for i, entry in enumerate(STATIC_TABLE)}
_STATIC_NAME = {}
for _i, (_n, _v) in enumerate(STATIC_TABLE):
    _STATIC_NAME.setdefault(_n, _i + 1)

_ENTRY_OVERHEAD = 32  # RFC 7541 §4.1

# memo-cache size caps: header vocabularies are tiny in practice (method
# paths, status codes, content types); the caps only bound a pathological
# all-unique workload, where caching is pointless anyway
_STR_CACHE_MAX = 1024
_STR_CACHE_VALUE_MAX = 256
_FRAGMENT_CACHE_MAX = 2048


class HPACKError(Exception):
    pass


# -- integer / string primitives (§5) ----------------------------------------

def encode_int(value: int, prefix_bits: int, flags: int = 0) -> bytearray:
    limit = (1 << prefix_bits) - 1
    out = bytearray()
    if value < limit:
        out.append(flags | value)
        return out
    out.append(flags | limit)
    value -= limit
    while value >= 0x80:
        out.append(0x80 | (value & 0x7F))
        value >>= 7
    out.append(value)
    return out


def decode_int(data: bytes, pos: int, prefix_bits: int) -> tuple[int, int]:
    limit = (1 << prefix_bits) - 1
    if pos >= len(data):
        raise HPACKError("truncated integer")
    value = data[pos] & limit
    pos += 1
    if value < limit:
        return value, pos
    shift = 0
    while True:
        if pos >= len(data):
            raise HPACKError("truncated integer continuation")
        b = data[pos]
        pos += 1
        value += (b & 0x7F) << shift
        shift += 7
        if shift > 62:
            raise HPACKError("integer overflow")
        if not b & 0x80:
            return value, pos


# -- Huffman (Appendix B) -----------------------------------------------------

_DECODE = {(bits, code): sym for sym, (code, bits) in enumerate(HUFFMAN_CODES)}
_EOS_PREFIXES = set()
_eos_code, _eos_bits = HUFFMAN_CODES[256]
for _n in range(1, 8):
    _EOS_PREFIXES.add((_n, _eos_code >> (_eos_bits - _n)))


def huffman_encode(data: bytes) -> bytes:
    acc = 0
    nbits = 0
    out = bytearray()
    for sym in data:
        code, bits = HUFFMAN_CODES[sym]
        acc = (acc << bits) | code
        nbits += bits
        while nbits >= 8:
            nbits -= 8
            out.append((acc >> nbits) & 0xFF)
    if nbits:
        pad = 8 - nbits
        out.append(((acc << pad) | ((1 << pad) - 1)) & 0xFF)
    return bytes(out)


def huffman_decode(data: bytes) -> bytes:
    out = bytearray()
    code = 0
    nbits = 0
    for byte in data:
        for shift in range(7, -1, -1):
            code = (code << 1) | ((byte >> shift) & 1)
            nbits += 1
            sym = _DECODE.get((nbits, code))
            if sym is not None:
                if sym == 256:
                    raise HPACKError("EOS symbol in huffman stream")
                out.append(sym)
                code = 0
                nbits = 0
            elif nbits > 30:
                raise HPACKError("invalid huffman code")
    if nbits >= 8 or (nbits and (nbits, code) not in _EOS_PREFIXES):
        raise HPACKError("invalid huffman padding")
    return bytes(out)


def encode_string(data: bytes, huffman: bool = True) -> bytearray:
    if huffman:
        encoded = huffman_encode(data)
        if len(encoded) < len(data):
            out = encode_int(len(encoded), 7, 0x80)
            out.extend(encoded)
            return out
    out = encode_int(len(data), 7, 0x00)
    out.extend(data)
    return out


def decode_string(data: bytes, pos: int) -> tuple[bytes, int]:
    if pos >= len(data):
        raise HPACKError("truncated string")
    is_huffman = bool(data[pos] & 0x80)
    length, pos = decode_int(data, pos, 7)
    if pos + length > len(data):
        raise HPACKError("string exceeds block")
    raw = bytes(data[pos : pos + length])
    return (huffman_decode(raw) if is_huffman else raw), pos + length


# -- dynamic table ------------------------------------------------------------

class _DynamicTable:
    """Eviction-ordered dynamic table with an O(1) reverse index.

    ``entries[i]`` holds the entry inserted ``i`` insertions ago (newest
    first, per §2.3.2). The reverse index maps (name, value) and name to
    the newest matching insertion's ABSOLUTE id (a monotonically growing
    counter), so ``find`` never re-walks the list: the entry's current
    position is ``_base - abs_id`` regardless of how many inserts and
    evictions happened since. Mappings are dropped at eviction only when
    they still point at the evicted insertion (a newer duplicate wins)."""

    def __init__(self, max_size: int = 4096):
        self.entries: list[tuple[bytes, bytes]] = []
        self.size = 0
        self.max_size = max_size
        self.cap = max_size  # protocol ceiling (SETTINGS_HEADER_TABLE_SIZE)
        self._base = 0                # total insertions ever
        self._pair_abs: dict[tuple[bytes, bytes], int] = {}
        self._name_abs: dict[bytes, int] = {}

    def _pop_last(self) -> None:
        abs_id = self._base - (len(self.entries) - 1)
        en, ev = self.entries.pop()
        self.size -= len(en) + len(ev) + _ENTRY_OVERHEAD
        if self._pair_abs.get((en, ev)) == abs_id:
            del self._pair_abs[(en, ev)]
        if self._name_abs.get(en) == abs_id:
            del self._name_abs[en]

    def add(self, name: bytes, value: bytes) -> None:
        need = len(name) + len(value) + _ENTRY_OVERHEAD
        while self.entries and self.size + need > self.max_size:
            self._pop_last()
        if need <= self.max_size:
            self._base += 1
            self.entries.insert(0, (name, value))
            self.size += need
            self._pair_abs[(name, value)] = self._base
            self._name_abs[name] = self._base

    def resize(self, new_max: int) -> None:
        if new_max > self.cap:
            raise HPACKError(f"table size {new_max} above ceiling {self.cap}")
        self.max_size = new_max
        while self.entries and self.size > self.max_size:
            self._pop_last()

    def get(self, index: int) -> tuple[bytes, bytes]:
        # index is 1-based over static + dynamic (§2.3.3)
        if 1 <= index <= len(STATIC_TABLE):
            return STATIC_TABLE[index - 1]
        d = index - len(STATIC_TABLE) - 1
        if 0 <= d < len(self.entries):
            return self.entries[d]
        raise HPACKError(f"invalid index {index}")

    def find(self, name: bytes, value: bytes) -> tuple[int, bool]:
        """-> (index, exact). index 0 = not found. Preference order
        (static exact, dynamic exact, static name, dynamic name) and
        newest-duplicate-wins match the linear scan this replaced, so
        encoded blocks are byte-identical."""
        exact = _STATIC_FULL.get((name, value))
        if exact:
            return exact, True
        abs_id = self._pair_abs.get((name, value))
        if abs_id is not None:
            return len(STATIC_TABLE) + 1 + (self._base - abs_id), True
        name_idx = _STATIC_NAME.get(name)
        if name_idx:
            return name_idx, False
        abs_id = self._name_abs.get(name)
        if abs_id is not None:
            return len(STATIC_TABLE) + 1 + (self._base - abs_id), False
        return 0, False


# -- encoder / decoder --------------------------------------------------------

def _norm(h: "str | bytes") -> bytes:
    return h.encode("ascii") if isinstance(h, str) else h


_NAME_NORM: dict = {}


def _norm_name(h: "str | bytes") -> bytes:
    """``_norm(h).lower()`` with a small memo — header NAMES draw from a
    tiny vocabulary and the per-call encode+lower allocations showed up
    in the transport profile."""
    v = _NAME_NORM.get(h)
    if v is None:
        v = _norm(h).lower()
        if len(_NAME_NORM) < _STR_CACHE_MAX:
            _NAME_NORM[h] = v
    return v


# (name, value) -> precomputed §6.1 indexed bytes for every static-exact
# entry. Static indices never move, so these are valid under ANY dynamic
# table state — the unconditionally-safe half of the encode cache.
_STATIC_EXACT_BYTES = {entry: bytes(encode_int(i + 1, 7, 0x80))
                       for i, entry in enumerate(STATIC_TABLE)}

# (name, value) -> stateless block fragment (see encode_stateless)
_STATELESS_FRAGMENTS: dict = {}


def encode_stateless(headers) -> bytes:
    """Encode a header block that neither reads nor writes ANY dynamic
    table state: static-exact fields as §6.1 indexed, everything else as
    §6.2.2 literal-without-indexing (static name index when one exists).

    Such a block is valid at any point in a connection's lifetime and
    leaves the peer's decoder table untouched, so it can be pre-encoded
    ONCE PER SERVER (response headers, trailer templates) and written
    from any thread without holding the connection's encoder lock — the
    HPACK half of the first-token fast path. Fragments memoize per
    (name, value): the dynamic-table-safe encode cache."""
    out = bytearray()
    for name, value in headers:
        name, value = _norm_name(name), _norm(value)
        key = (name, value)
        frag = _STATELESS_FRAGMENTS.get(key)
        if frag is None:
            frag = _STATIC_EXACT_BYTES.get(key)
            if frag is None:
                nidx = _STATIC_NAME.get(name, 0)
                buf = encode_int(nidx, 4, 0x00)
                if not nidx:
                    buf.extend(encode_string(name))
                buf.extend(encode_string(value))
                frag = bytes(buf)
            # memoize only short values: grpc-message trailers carry
            # per-request error text — high-cardinality, arbitrary
            # length — which would pin memory AND crowd out the hot
            # pairs; clear-on-full (not stop-on-full) keeps the cache
            # live for new legitimate pairs after churn
            if len(value) <= _STR_CACHE_VALUE_MAX:
                if len(_STATELESS_FRAGMENTS) >= _FRAGMENT_CACHE_MAX:
                    _STATELESS_FRAGMENTS.clear()
                _STATELESS_FRAGMENTS[key] = frag
        out += frag
    return bytes(out)


class Encoder:
    def __init__(self, max_table_size: int = 4096, memo: bool = True):
        self.table = _DynamicTable(max_table_size)
        self.huffman = True
        self.indexing = True
        # memo=False disables the string-encode cache (the legacy arm of
        # tools/transport_bench.py); output bytes are identical either way
        self.memo = memo
        self._str_cache: dict = {}
        self._pending_size_update: int | None = None
        self._pending_size_min: int | None = None

    def _estr(self, data: bytes) -> "bytes | bytearray":
        """encode_string with a memo: the Huffman bit-packing loop is the
        dominant per-header cost, and header strings repeat heavily
        (paths, content types, status codes). Pure-function cache, so
        cached and uncached output are byte-identical."""
        if not self.memo or len(data) > _STR_CACHE_VALUE_MAX:
            return encode_string(data, self.huffman)
        key = (data, self.huffman)
        out = self._str_cache.get(key)
        if out is None:
            if len(self._str_cache) >= _STR_CACHE_MAX:
                self._str_cache.clear()
            out = bytes(encode_string(data, self.huffman))
            self._str_cache[key] = out
        return out

    def set_max_table_size(self, size: int) -> None:
        """Apply the peer's SETTINGS_HEADER_TABLE_SIZE: shrink our encoding
        table to fit and schedule the §6.3 dynamic-table-size update that
        must open the next header block (RFC 7541 §4.2). Entries over the
        new size are evicted here, so find() can never emit an indexed
        reference the peer's shrunken table cannot resolve. Several changes
        between header blocks track the MINIMUM too — §4.2 requires the
        smallest intermediate size be signaled (so a shrink-then-grow still
        flushes the peer's table) before the final one."""
        size = min(size, self.table.cap)
        self.table.resize(size)
        self._pending_size_min = (size if self._pending_size_min is None
                                  else min(self._pending_size_min, size))
        self._pending_size_update = size

    def encode(self, headers) -> bytes:
        out = bytearray()
        if self._pending_size_update is not None:
            if self._pending_size_min < self._pending_size_update:
                out.extend(encode_int(self._pending_size_min, 5, 0x20))
            out.extend(encode_int(self._pending_size_update, 5, 0x20))
            self._pending_size_update = None
            self._pending_size_min = None
        for name, value in headers:
            name, value = _norm_name(name), _norm(value)
            idx, exact = self.table.find(name, value)
            if exact:
                if idx <= len(STATIC_TABLE):
                    out += _STATIC_EXACT_BYTES[(name, value)]
                else:
                    out.extend(encode_int(idx, 7, 0x80))  # §6.1 indexed
            elif not self.indexing:
                out.extend(encode_int(idx, 4, 0x00))  # §6.2.2 (idx may be 0)
                if not idx:
                    out.extend(self._estr(name))
                out.extend(self._estr(value))
            elif idx:
                # §6.2.1 literal with incremental indexing, indexed name
                out.extend(encode_int(idx, 6, 0x40))
                out.extend(self._estr(value))
                self.table.add(name, value)
            else:
                out.extend(encode_int(0, 6, 0x40))  # new name
                out.extend(self._estr(name))
                out.extend(self._estr(value))
                self.table.add(name, value)
        return bytes(out)


class Decoder:
    def __init__(self, max_table_size: int = 4096):
        self.table = _DynamicTable(max_table_size)

    def decode(self, data: bytes) -> list[tuple[bytes, bytes]]:
        headers: list[tuple[bytes, bytes]] = []
        pos = 0
        while pos < len(data):
            b = data[pos]
            if b & 0x80:  # §6.1 indexed
                idx, pos = decode_int(data, pos, 7)
                if idx == 0:
                    raise HPACKError("index 0 in indexed representation")
                headers.append(self.table.get(idx))
            elif b & 0x40:  # §6.2.1 literal, incremental indexing
                idx, pos = decode_int(data, pos, 6)
                name, value, pos = self._literal(data, pos, idx)
                self.table.add(name, value)
                headers.append((name, value))
            elif b & 0x20:  # §6.3 dynamic table size update
                size, pos = decode_int(data, pos, 5)
                self.table.resize(size)
            else:  # §6.2.2/§6.2.3 literal without indexing / never indexed
                idx, pos = decode_int(data, pos, 4)
                name, value, pos = self._literal(data, pos, idx)
                headers.append((name, value))
        return headers

    def _literal(self, data: bytes, pos: int, idx: int):
        if idx:
            name = self.table.get(idx)[0]
        else:
            name, pos = decode_string(data, pos)
        value, pos = decode_string(data, pos)
        return name, value, pos
