"""Native gRPC server: HTTP/2 transport + method dispatch + interceptors.

Reference: pkg/gofr/grpc.go:20-46 — grpc-go server on GRPC_PORT with
chained unary interceptors (panic recovery + logging/tracing,
grpc.go:22-26) — and grpc/log.go:19-68 (RPCLog with µs latency + OTel
span per RPC). This server reproduces that contract on its own wire
layer, and adds SERVER STREAMING, which the reference lacks
(SURVEY §3.3: "unary only") but the Llama token-stream target requires.

Model: thread per connection (frame loop) + thread per stream (handler) —
the Python mirror of grpc-go's goroutine-per-stream. Writes are serialized
by FrameIO; DATA sends respect both flow-control windows.
"""

from __future__ import annotations

import queue
import socket
import struct
import threading
import time
import traceback
import urllib.parse

from . import http2 as h2
from . import service as svc
from .hpack import Decoder, Encoder, encode_stateless
from .. import chaos, tracing, wire
from ..resilience import (Deadline, deadline_scope, parse_slo_class,
                          slo_scope)
from ..wire import Outbox

_GRPC_CONTENT_TYPES = ("application/grpc",)
_TIMEOUT_UNITS = {"H": 3600.0, "M": 60.0, "S": 1.0, "m": 1e-3, "u": 1e-6, "n": 1e-9}


def parse_grpc_timeout(val: str | None) -> float | None:
    if not val:
        return None
    try:
        return int(val[:-1]) * _TIMEOUT_UNITS[val[-1]]
    except (KeyError, ValueError):
        return None


class _Stream:
    __slots__ = ("id", "headers", "recv_q", "buffer", "send_window",
                 "cancelled", "end_received", "headers_sent", "worker",
                 "recv_debt")

    def __init__(self, sid: int, headers: dict[str, str], initial_window: int):
        self.id = sid
        self.headers = headers
        self.recv_q: queue.Queue = queue.Queue()
        self.buffer = bytearray()
        self.send_window = h2.FlowWindow(initial_window)
        self.cancelled = threading.Event()
        self.end_received = False
        self.headers_sent = False
        self.worker: threading.Thread | None = None
        self.recv_debt = 0  # bytes received since the last WINDOW_UPDATE


class _Connection:
    """One accepted socket: owns the frame loop and all stream state."""

    def __init__(self, sock: socket.socket, addr, server: "GRPCServer"):
        self.options = server.options
        self.io = h2.FrameIO(sock, vectored=self.options.vectored)
        self.addr = addr
        self.server = server
        self.encoder = Encoder(memo=self.options.hpack_memo)
        self.decoder = Decoder()
        self._replenisher = h2.WindowReplenisher(self.io,
                                                 self.options.lazy_window)
        self._enc_lock = threading.Lock()
        self.conn_window = h2.FlowWindow(h2.DEFAULT_WINDOW)
        self.peer_initial_window = h2.DEFAULT_WINDOW
        self.streams: dict[int, _Stream] = {}
        self._streams_lock = threading.Lock()
        self._goaway = False
        self._last_stream = 0
        # header block being assembled across HEADERS/CONTINUATION
        self._hdr_sid = 0
        self._hdr_block = b""
        self._hdr_end_stream = False

    # -- lifecycle -----------------------------------------------------------
    def run(self) -> None:
        try:
            self.io.read_preface()
            self.io.send_frame(h2.SETTINGS, 0, 0, h2.encode_settings({
                h2.SETTINGS_HEADER_TABLE_SIZE: 4096,
                h2.SETTINGS_MAX_FRAME_SIZE: h2.DEFAULT_MAX_FRAME,
                h2.SETTINGS_MAX_CONCURRENT_STREAMS: 1024,
            }))
            while True:
                frame = self.io.recv_frame()
                self._dispatch(frame)
        except (EOFError, OSError):
            pass
        except h2.ConnectionError_ as e:
            self._send_goaway(e.code, str(e))
        except Exception as e:  # noqa: BLE001
            log = self.server.logger
            if log is not None:
                log.error({"event": "grpc connection crashed", "error": repr(e)})
            self._send_goaway(h2.INTERNAL_ERROR, "internal error")
        finally:
            self._teardown()

    def _teardown(self) -> None:
        with self._streams_lock:
            streams = list(self.streams.values())
            self.streams.clear()
        for st in streams:
            st.cancelled.set()
            st.send_window.kill()
            st.recv_q.put(None)
        self.conn_window.kill()
        self.io.close()
        self.server._conn_done(self)

    def _send_goaway(self, code: int, msg: str = "") -> None:
        try:
            payload = struct.pack(">II", self._last_stream, code) + msg.encode()[:128]
            self.io.send_frame(h2.GOAWAY, 0, 0, payload)
        except (EOFError, OSError):  # noqa: GL303 — best-effort GOAWAY:
            pass  # the peer this goodbye is FOR is the thing that died

    # -- frame dispatch ------------------------------------------------------
    def _dispatch(self, f: h2.Frame) -> None:
        if self._hdr_sid and f.type != h2.CONTINUATION:
            raise h2.ConnectionError_(h2.PROTOCOL_ERROR,
                                      "expected CONTINUATION")
        if f.type == h2.SETTINGS:
            self._on_settings(f)
        elif f.type == h2.HEADERS:
            self._on_headers(f)
        elif f.type == h2.CONTINUATION:
            self._on_continuation(f)
        elif f.type == h2.DATA:
            self._on_data(f)
        elif f.type == h2.WINDOW_UPDATE:
            self._on_window_update(f)
        elif f.type == h2.RST_STREAM:
            self._on_rst(f)
        elif f.type == h2.PING:
            if not f.flags & h2.FLAG_ACK:
                self.io.send_frame(h2.PING, h2.FLAG_ACK, 0, f.payload)
        elif f.type == h2.GOAWAY:
            self._goaway = True
        elif f.type == h2.PUSH_PROMISE:
            raise h2.ConnectionError_(h2.PROTOCOL_ERROR, "client push")
        # PRIORITY and unknown frame types are ignored (RFC 9113 §4.1)

    def _on_settings(self, f: h2.Frame) -> None:
        if f.flags & h2.FLAG_ACK:
            return
        if f.stream_id != 0:
            raise h2.ConnectionError_(h2.PROTOCOL_ERROR, "SETTINGS on stream")
        settings = h2.decode_settings(f.payload)
        if h2.SETTINGS_MAX_FRAME_SIZE in settings:
            self.io.peer_max_frame = settings[h2.SETTINGS_MAX_FRAME_SIZE]
        if h2.SETTINGS_HEADER_TABLE_SIZE in settings:
            with self._enc_lock:
                self.encoder.set_max_table_size(
                    settings[h2.SETTINGS_HEADER_TABLE_SIZE])
        if h2.SETTINGS_INITIAL_WINDOW_SIZE in settings:
            new = settings[h2.SETTINGS_INITIAL_WINDOW_SIZE]
            if new > h2.MAX_WINDOW:
                raise h2.ConnectionError_(h2.FLOW_CONTROL_ERROR, "bad window")
            delta = new - self.peer_initial_window
            self.peer_initial_window = new
            with self._streams_lock:
                for st in self.streams.values():
                    st.send_window.adjust(delta)
        self.io.send_frame(h2.SETTINGS, h2.FLAG_ACK, 0)

    def _on_headers(self, f: h2.Frame) -> None:
        if f.stream_id == 0 or f.stream_id % 2 == 0:
            raise h2.ConnectionError_(h2.PROTOCOL_ERROR, "bad stream id")
        block = h2.strip_padding(f)
        if f.flags & h2.FLAG_END_HEADERS:
            self._open_stream(f.stream_id, block,
                              bool(f.flags & h2.FLAG_END_STREAM))
        else:
            self._hdr_sid = f.stream_id
            self._hdr_block = block
            self._hdr_end_stream = bool(f.flags & h2.FLAG_END_STREAM)

    def _on_continuation(self, f: h2.Frame) -> None:
        if f.stream_id != self._hdr_sid:
            raise h2.ConnectionError_(h2.PROTOCOL_ERROR, "bad CONTINUATION")
        self._hdr_block += f.payload
        if f.flags & h2.FLAG_END_HEADERS:
            sid, block = self._hdr_sid, self._hdr_block
            end = self._hdr_end_stream
            self._hdr_sid, self._hdr_block = 0, b""
            self._open_stream(sid, block, end)

    def _open_stream(self, sid: int, block: bytes, end_stream: bool) -> None:
        headers = {k.decode("ascii"): v.decode("utf-8", "replace")
                   for k, v in self.decoder.decode(block)}
        if sid <= self._last_stream:
            raise h2.ConnectionError_(h2.PROTOCOL_ERROR, "stream id reuse")
        self._last_stream = sid
        st = _Stream(sid, headers, self.peer_initial_window)
        st.end_received = end_stream
        if end_stream:
            st.recv_q.put(None)
        with self._streams_lock:
            if self._goaway:
                self.io.send_frame(h2.RST_STREAM, 0, sid,
                                   struct.pack(">I", h2.REFUSED_STREAM))
                return
            self.streams[sid] = st
        st.worker = threading.Thread(target=self.server._handle_stream,
                                     args=(self, st), daemon=True,
                                     name=f"grpc-stream-{sid}")
        st.worker.start()

    def _on_data(self, f: h2.Frame) -> None:
        with self._streams_lock:
            st = self.streams.get(f.stream_id)
        if st is None:
            # closed/unknown stream: still account connection flow control
            if f.payload:
                self._replenisher.on_data(None, f.stream_id,
                                          len(f.payload), False)
            return
        data = h2.strip_padding(f)
        st.buffer.extend(data)
        # gRPC length-prefixed messages (compressed-flag byte + u32 length)
        while len(st.buffer) >= 5:
            compressed, length = st.buffer[0], int.from_bytes(st.buffer[1:5], "big")
            if len(st.buffer) < 5 + length:
                break
            msg = bytes(st.buffer[5 : 5 + length])
            del st.buffer[: 5 + length]
            if compressed:
                st.recv_q.put(svc.GRPCError(svc.UNIMPLEMENTED,
                                            "compression not supported"))
            else:
                st.recv_q.put(msg)
        if f.flags & h2.FLAG_END_STREAM:
            st.end_received = True
            st.recv_q.put(None)
        # replenish receive windows (we buffer in-process, never stall reads)
        if f.payload:
            self._replenisher.on_data(st, st.id, len(f.payload),
                                      not st.end_received)

    def _on_window_update(self, f: h2.Frame) -> None:
        if len(f.payload) != 4:
            raise h2.ConnectionError_(h2.FRAME_SIZE_ERROR, "bad WINDOW_UPDATE")
        inc = int.from_bytes(f.payload, "big") & 0x7FFFFFFF
        if inc == 0:
            raise h2.ConnectionError_(h2.PROTOCOL_ERROR, "zero window increment")
        if f.stream_id == 0:
            self.conn_window.credit(inc)
        else:
            with self._streams_lock:
                st = self.streams.get(f.stream_id)
            if st is not None:
                st.send_window.credit(inc)

    def _on_rst(self, f: h2.Frame) -> None:
        with self._streams_lock:
            st = self.streams.pop(f.stream_id, None)
        if st is not None:
            st.cancelled.set()
            st.send_window.kill()
            st.recv_q.put(None)

    # -- stream sends (called from worker threads) ---------------------------
    def send_headers(self, st: _Stream, headers, end_stream: bool = False) -> None:
        flags = h2.FLAG_END_HEADERS | (h2.FLAG_END_STREAM if end_stream else 0)
        if self.options.hpack_memo:
            # stateless block (static-exact + literal-without-indexing):
            # touches no dynamic table, so there is no ordering
            # constraint with other encodes and no lock to hold
            self.io.send_frame(h2.HEADERS, flags, st.id,
                               encode_stateless(headers))
            return
        # HPACK is stateful: blocks must hit the wire in encode order, so
        # the send stays under the encoder lock.
        with self._enc_lock:
            block = self.encoder.encode(headers)
            self.io.send_frame(h2.HEADERS, flags, st.id, block)

    def send_message(self, st: _Stream, payload: bytes,
                     headers=None, stages: "dict | None" = None) -> None:
        """One gRPC length-prefixed message as flow-controlled DATA.

        ``headers``: response headers to coalesce with the FIRST data
        frame in a single socket write — the first-token fast path for
        streaming RPCs (one packet on the wire instead of HEADERS then
        DATA; saves a syscall and a client-reader wakeup on the latency
        path the BASELINE gRPC-TTFT target measures).

        ``stages``: optional dict the coalesced HEADERS+DATA send fills
        with monotonic stamps (enc0/enc1/write0/write1) — the source of
        the grpc.hpack / grpc.frame-write TTFT decomposition spans."""
        data = svc.grpc_frame(payload)
        view = memoryview(data)
        while view:
            if st.cancelled.is_set():
                raise svc.GRPCError(svc.CANCELLED, "client cancelled")
            want = min(len(view), self.io.peer_max_frame)
            n_stream = st.send_window.consume(want, timeout=30.0)
            n = self.conn_window.consume(n_stream, timeout=30.0)
            if n < n_stream:  # refund stream credit the connection couldn't cover
                st.send_window.credit(n_stream - n)
            if headers is not None:
                t_enc0 = time.monotonic()
                if self.options.hpack_memo:
                    block = self.server.resp_block(headers)
                    t_enc1 = time.monotonic()
                    self.io.send_frames([
                        (h2.HEADERS, h2.FLAG_END_HEADERS, st.id, block),
                        (h2.DATA, 0, st.id, bytes(view[:n]))])
                else:
                    with self._enc_lock:  # stateful: encode+send in order
                        block = self.encoder.encode(headers)
                        t_enc1 = time.monotonic()
                        self.io.send_frames([
                            (h2.HEADERS, h2.FLAG_END_HEADERS, st.id, block),
                            (h2.DATA, 0, st.id, bytes(view[:n]))])
                if stages is not None:
                    stages.update(enc0=t_enc0, enc1=t_enc1, write0=t_enc1,
                                  write1=time.monotonic())
                # flag only AFTER the frames hit the wire: an earlier
                # flow-control timeout/cancel must leave headers_sent
                # False so _finish still emits a full trailers-only
                # response (:status + grpc-status), not bare trailers
                st.headers_sent = True
                headers = None
            else:
                self.io.send_frame(h2.DATA, 0, st.id, bytes(view[:n]))
            view = view[n:]

    def close_stream(self, st: _Stream) -> None:
        with self._streams_lock:
            self.streams.pop(st.id, None)


class _PushSender:
    """One stream's zero-handoff delivery state (GRPCServer._serve_push).

    All response DATA for the stream flows through ONE wire.Outbox in
    FIFO order, drained by whichever thread is available:

      - the producing thread (the engine serving loop, via the
        GenStream sink) appends and pumps NONBLOCKING — flow-control
        credit is claimed with try_consume and bytes leave through the
        writer's MSG_DONTWAIT path, so token delivery can never stall
        behind a slow client;
      - on any obstacle (no credit, oversized message, serialize
        failure, deadline, cancel) the sender DOWNGRADES permanently:
        later items go back to the stream queue and the RPC's worker
        thread serves them with the blocking path. Latency is already
        lost at that point; ordering never is, because every DATA byte
        passes through the outbox.
    """

    __slots__ = ("server", "conn", "st", "codec", "map_fn", "source",
                 "deadline", "outbox", "downgraded", "_spans_done")

    def __init__(self, server: "GRPCServer", conn: _Connection, st: _Stream,
                 codec, map_fn, source, deadline: float | None):
        self.server = server
        self.conn = conn
        self.st = st
        self.codec = codec
        self.map_fn = map_fn
        self.source = source
        self.deadline = deadline
        self.outbox = Outbox(self._drain)
        self.downgraded = False
        self._spans_done = False

    # -- producing thread ----------------------------------------------------
    def sink(self, item) -> bool:
        if self.downgraded or self.st.cancelled.is_set():
            return False
        if self.deadline is not None and time.monotonic() > self.deadline:
            self.downgraded = True  # the worker raises DEADLINE_EXCEEDED
            return False
        try:
            payload = self.codec.serialize(self.map_fn(item))
        except Exception:
            self.downgraded = True
            return False
        if len(payload) + 5 > self.conn.io.peer_max_frame:
            self.downgraded = True  # multi-frame message: worker path
            return False
        self.outbox.append(payload)
        try:
            self.outbox.pump(block=False)
        except Exception:
            self.downgraded = True
            self._wake_worker()  # committed bytes need a flusher
            return True
        if self.outbox.stalled:
            self.downgraded = True
            # the stalled item has NO other waker: the worker is parked
            # in q.get and the next token may be a decode block away —
            # without this the first byte waits for the second token
            self._wake_worker()
        return True

    def _wake_worker(self) -> None:
        w = getattr(self.source, "wake", None)
        if w is not None:
            w()

    # -- worker thread -------------------------------------------------------
    def send(self, item) -> None:
        self.outbox.append(self.codec.serialize(self.map_fn(item)))
        self.outbox.pump(block=True)

    def finish(self) -> None:
        self.outbox.pump(block=True)
        # a deferred nonblocking write may have parked bytes in the
        # WRITER's backlog (one layer below the outbox) — drain that too
        self.conn.io.flush()

    # -- outbox drain (single flusher at a time; see wire.Outbox) ------------
    def _drain(self, batch, block: bool) -> int:
        conn, st = self.conn, self.st
        if block:
            for payload in batch:
                got = time.monotonic()
                if st.headers_sent:
                    conn.send_message(st, payload)
                else:
                    stages: dict = {}
                    conn.send_message(st, payload,
                                      headers=_response_headers(),
                                      stages=stages)
                    self._spans(got, stages)
            return len(batch)
        frames = []
        stages = {}
        got = time.monotonic()
        n = 0
        for payload in batch:
            if st.cancelled.is_set():
                break
            data = svc.grpc_frame(payload)
            if len(data) > conn.io.peer_max_frame:
                break  # the worker sends it multi-frame
            if not st.send_window.try_consume(len(data)):
                break
            if not conn.conn_window.try_consume(len(data)):
                st.send_window.credit(len(data))
                break
            if not st.headers_sent:
                if not conn.options.hpack_memo:
                    # stateful HPACK requires encode->wire atomicity
                    # under the encoder lock; leave the first message to
                    # the worker's send_message, which holds it properly
                    st.send_window.credit(len(data))
                    conn.conn_window.credit(len(data))
                    break
                stages["enc0"] = time.monotonic()
                block_b = self.server.resp_block(_response_headers())
                stages["enc1"] = time.monotonic()
                frames.append((h2.HEADERS, h2.FLAG_END_HEADERS, st.id,
                               block_b))
                st.headers_sent = True
            frames.append((h2.DATA, 0, st.id, data))
            n += 1
        if frames:
            t0 = time.monotonic()
            on_wire = conn.io.send_frames(frames, block=False)
            if "enc0" in stages:
                stages["write0"], stages["write1"] = t0, time.monotonic()
                self._spans(got, stages)
            if not on_wire:
                # bytes parked in the writer backlog (socket full /
                # write lock contended): same no-waker hazard as an
                # outbox stall one layer up — the backlog would sit
                # until the NEXT write on the connection. Downgrade and
                # wake the worker, whose finish() flushes the writer.
                self.downgraded = True
                self._wake_worker()
        return n

    def _spans(self, got: float, stages: dict) -> None:
        if self._spans_done:
            return
        self._spans_done = True
        self.server._first_send_spans(self.st, self.source, got, stages)


class GRPCServer:
    """Accept loop + RPC dispatch with recovery/logging/tracing interceptors
    (reference grpc.go:22-26 chain order)."""

    def __init__(self, services, port: int, container=None,
                 options: "h2.TransportOptions | None" = None):
        self.services: dict[str, svc.GRPCService] = {
            s.name: s for s in services}
        self._draining = False
        self._drain_retry_after: float | None = None
        if "grpc.health.v1.Health" not in self.services:
            self._install_health_service()
        self.port = port
        self.container = container
        self.logger = container.logger if container is not None else None
        self.tracer = getattr(container, "tracer", None)
        self.options = options or h2.TransportOptions()
        # the static response header block, pre-encoded ONCE per server:
        # stateless (see hpack.encode_stateless), so it is valid on
        # every connection at any point in its lifetime
        self._resp_block = encode_stateless(_RESPONSE_HEADERS)
        self._sock: socket.socket | None = None
        self._conns: set[_Connection] = set()
        self._conns_lock = threading.Lock()
        self._accept_thread: threading.Thread | None = None
        self._stopping = False

    def _install_health_service(self) -> None:
        """Built-in readiness service (grpc.health.v1 shape, JSON codec):
        load balancers poll Check and see NOT_SERVING the moment a
        graceful drain starts — BEFORE the engine stops taking work —
        so routing moves away while in-flight streams finish."""
        health = svc.GRPCService("grpc.health.v1.Health")

        def check(ctx, req):
            return {"status": "NOT_SERVING" if self._draining else "SERVING"}

        health.unary("Check", check)
        self.services[health.name] = health

    def start_draining(self, retry_after: float | None = None) -> None:
        """Flip readiness for a graceful drain: health reports
        NOT_SERVING and NEW RPCs are refused with UNAVAILABLE (+
        retry-after trailer) while streams already dispatched run to
        completion over their live connections."""
        self._draining = True
        self._drain_retry_after = retry_after
        if self.logger is not None:
            self.logger.info({"event": "grpc server draining",
                              "retry_after_s": retry_after})

    def resp_block(self, headers) -> bytes:
        """Pre-encoded stateless block for the standard response
        headers; arbitrary header lists fall through to
        encode_stateless (whose per-pair fragments memoize)."""
        if tuple(headers) == _RESPONSE_HEADERS:
            return self._resp_block
        return encode_stateless(headers)

    # -- lifecycle (reference grpc.go:31-46 Run) -----------------------------
    def start(self) -> None:
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("0.0.0.0", self.port))
        self._sock.listen(128)
        self.port = self._sock.getsockname()[1]
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               name="gofr-grpc-accept",
                                               daemon=True)
        self._accept_thread.start()
        if self.logger is not None:
            self.logger.info({"event": "grpc server listening",
                              "port": self.port,
                              "services": sorted(self.services)})

    def _accept_loop(self) -> None:
        while not self._stopping:
            try:
                sock, addr = self._sock.accept()
            except OSError:
                return
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn = _Connection(sock, addr, self)
            with self._conns_lock:
                self._conns.add(conn)
            threading.Thread(target=conn.run, daemon=True,
                             name=f"gofr-grpc-conn-{addr[1]}").start()

    def _conn_done(self, conn: _Connection) -> None:
        with self._conns_lock:
            self._conns.discard(conn)

    def stop(self) -> None:
        self._stopping = True
        if self._sock is not None:
            try:
                # shutdown() BEFORE close(): on Linux a thread blocked in
                # accept() is NOT woken by close() from another thread
                # (the in-progress syscall pins the open file
                # description) — shutdown is what interrupts it. Without
                # this every stopped server leaked its accept thread
                # (caught by the conftest session-teardown assertion).
                self._sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._sock.close()
            except OSError:
                pass
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            c._send_goaway(h2.NO_ERROR)
            c.io.close()
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5.0)

    # -- RPC dispatch --------------------------------------------------------
    def _handle_stream(self, conn: _Connection, st: _Stream) -> None:
        path = st.headers.get(":path", "")
        start = time.monotonic()
        status, message = svc.OK, ""
        retry_after: float | None = None
        span = None
        if self.tracer is not None:
            span = self.tracer.start_span(
                f"grpc{path}", traceparent=st.headers.get("traceparent"),
                attributes={"rpc.system": "grpc", "rpc.method": path})
        try:
            chaos.fire(chaos.GRPC_STREAM)
            status, message = self._invoke(conn, st, path)
        except svc.GRPCError as e:
            status, message = e.code, e.message
            retry_after = getattr(e, "retry_after", None)
        except (EOFError, OSError, TimeoutError) as e:
            status, message = svc.UNAVAILABLE, f"transport: {e!r}"
        except Exception as e:  # noqa: BLE001 — recovery interceptor
            if hasattr(e, "status_code"):
                # framework HTTPError: one status vocabulary across
                # transports (DeadlineExceeded -> DEADLINE_EXCEEDED,
                # TooManyRequests shed -> RESOURCE_EXHAUSTED + retry-after)
                ge = svc.from_http_error(e)
                status, message = ge.code, ge.message
                retry_after = getattr(e, "retry_after", None)
            else:
                status, message = svc.INTERNAL, "internal error"
                if self.logger is not None:
                    self.logger.error({
                        "event": "grpc panic recovered",
                        "method": path, "error": repr(e),
                        "traceback": traceback.format_exc(limit=8)})
        finally:
            self._finish(conn, st, status, message, retry_after=retry_after)
            if span is not None:
                span.set_attribute("rpc.grpc.status_code", status)
                span.end()
            # RPCLog mirror of reference grpc/log.go:19-25
            if self.logger is not None:
                self.logger.info({
                    "id": span.trace_id if span is not None else "",
                    "method": path,
                    "status_code": status,
                    "duration": int((time.monotonic() - start) * 1e6),
                    "rpc": True,
                })

    def _invoke(self, conn: _Connection, st: _Stream, path: str):
        ct = st.headers.get("content-type", "")
        if not any(ct.startswith(t) for t in _GRPC_CONTENT_TYPES):
            raise svc.GRPCError(svc.INTERNAL, f"bad content-type {ct!r}")
        try:
            _, service_name, method_name = path.split("/")
        except ValueError:
            raise svc.GRPCError(svc.UNIMPLEMENTED, f"malformed path {path!r}")
        service = self.services.get(service_name)
        method = service.lookup(method_name) if service is not None else None
        if method is None:
            raise svc.GRPCError(svc.UNIMPLEMENTED,
                                f"unknown method {path!r}")
        if self._draining and service_name != "grpc.health.v1.Health":
            # readiness flipped first (App.stop grace window): streams
            # already dispatched finish; NEW ones are refused fast with
            # a retry hint. Health stays reachable so pollers observe
            # NOT_SERVING rather than a vanished endpoint.
            e = svc.GRPCError(svc.UNAVAILABLE, "server draining")
            e.retry_after = self._drain_retry_after
            raise e

        timeout = parse_grpc_timeout(st.headers.get("grpc-timeout"))
        deadline = time.monotonic() + timeout if timeout else None
        metadata = {k: v for k, v in st.headers.items()
                    if not k.startswith(":")}
        ctx = svc.GRPCContext(self.container, path, metadata,
                              deadline=deadline,
                              peer=f"{conn.addr[0]}:{conn.addr[1]}")
        ctx.cancelled = st.cancelled

        def check_alive():
            if st.cancelled.is_set():
                raise svc.GRPCError(svc.CANCELLED, "client cancelled")
            if deadline is not None and time.monotonic() > deadline:
                raise svc.GRPCError(svc.DEADLINE_EXCEEDED, "deadline exceeded")

        def one_message():
            try:
                msg = st.recv_q.get(timeout=timeout or 60.0)
            except queue.Empty:
                raise svc.GRPCError(
                    svc.DEADLINE_EXCEEDED,
                    "no request message before deadline") from None
            if isinstance(msg, svc.GRPCError):
                raise msg
            if msg is None:
                return None
            try:
                return method.request_codec.deserialize(msg)
            except Exception as e:
                raise svc.GRPCError(svc.INVALID_ARGUMENT,
                                    f"bad request: {e!r}") from None

        # the wire deadline and SLO class become AMBIENT for the
        # handler thread: ctx.tpu.predict / generate pick them up
        # without per-call plumbing, so expired work is dropped before
        # the device sees it and ``slo-class: throughput`` metadata
        # routes the request through the batch-traffic line
        slo_class = parse_slo_class(metadata.get("slo-class"))
        # x-tenant-id metadata is the gRPC face of the HTTP
        # X-Tenant-Id header: same ambient scope, same registry
        # canonicalization downstream (tenancy/registry.py)
        tenant = (metadata.get("x-tenant-id") or "").strip() or None
        if tenant is not None:
            plane = getattr(self.container.tpu, "tenancy", None)
            if plane is not None:
                try:
                    tenant = plane.resolve(tenant).tenant_id
                except Exception:
                    pass
        rpc_span = tracing.current_span()
        if rpc_span is not None:
            # the RPC root span carries the class so the tail sampler's
            # per-class slow-tail p99 judges grpc traffic correctly
            rpc_span.set_attribute("slo_class", slo_class)
            if tenant is not None:
                rpc_span.set_attribute("tenant", tenant)
        from ..tenancy.registry import tenant_scope

        with deadline_scope(Deadline(deadline) if deadline is not None
                            else None), \
                slo_scope(slo_class), \
                tenant_scope(tenant):
            if method.client_streaming:
                # handler receives a lazy iterator over the request
                # stream; it ends at the client's half-close
                # (END_STREAM), errors surface in-loop, and
                # cancellation/deadline are re-checked per message
                def request_iter():
                    while True:
                        check_alive()
                        msg = one_message()
                        if msg is None:
                            return
                        yield msg

                check_alive()
                result = method.handler(ctx, request_iter())
            else:
                request = one_message()
                if request is None:
                    raise svc.GRPCError(svc.INVALID_ARGUMENT,
                                        "no request message")
                check_alive()
                result = method.handler(ctx, request)

            if method.server_streaming:
                try:
                    # zero-handoff requires the vectored writer: its sink
                    # writes MUST be nonblocking (the legacy wire path
                    # would park the producing engine thread on a slow
                    # client)
                    if (conn.options.zero_handoff and conn.options.vectored
                            and isinstance(result, svc.ServerStream)
                            and hasattr(result.source, "set_sink")):
                        self._serve_push(conn, st, method, result,
                                         check_alive, deadline)
                    else:
                        self._serve_iter(conn, st, method, result,
                                         check_alive)
                finally:
                    # ServerStream.close cancels the source (slot
                    # release); plain generators get their normal close
                    close = getattr(result, "close", None)
                    if close is not None:
                        close()
            else:
                check_alive()
                payload = method.response_codec.serialize(result)
                conn.send_message(st, payload, headers=_response_headers())
        return svc.OK, ""

    def _serve_iter(self, conn: _Connection, st: _Stream, method, result,
                    check_alive) -> None:
        """Pull-based server streaming: iterate the handler's generator
        on this worker thread (the pre-fast-path shape, still used for
        plain generator handlers and when zero_handoff is off)."""
        for item in result:
            check_alive()
            payload = method.response_codec.serialize(item)
            # coalesced HEADERS+DATA: one write for the first token;
            # send_message flips headers_sent once they're on the wire
            if st.headers_sent:
                conn.send_message(st, payload)
            else:
                got = time.monotonic()
                stages: dict = {}
                conn.send_message(st, payload, headers=_response_headers(),
                                  stages=stages)
                self._first_send_spans(st, result, got, stages)

    def _serve_push(self, conn: _Connection, st: _Stream, method, result,
                    check_alive, deadline) -> None:
        """Zero-handoff server streaming: the producing thread delivers
        serialized messages straight into the connection's write
        scheduler — first-token bytes go from the engine's _deliver to
        the socket without waking this worker. The worker only clears
        backpressure stalls, serves fallback items, and owns
        end-of-stream (trailers follow in _finish)."""
        src = result.source
        sender = _PushSender(self, conn, st, method.response_codec,
                             result.map_fn, src, deadline)
        src.set_sink(sender.sink)
        try:
            for item in src:  # items the sink declined + end-of-stream
                check_alive()
                if item is wire.WAKE:
                    sender.finish()  # flush a stalled outbox (sink woke us)
                    continue
                sender.send(item)
            check_alive()
            sender.finish()
        finally:
            # detach BEFORE trailers: a sink firing after END_STREAM
            # would corrupt the stream
            clear = getattr(src, "clear_sink", None)
            if clear is not None:
                clear()

    def _first_send_spans(self, st: _Stream, source, got: float,
                          stages: dict) -> None:
        """TTFT decomposition spans for the FIRST streamed message:
        grpc.handoff (producer _deliver -> transport), grpc.hpack
        (header block encode) and grpc.frame-write (the coalesced
        HEADERS+DATA write). Exported once per stream; bench.py's TTFT
        section and tools/transport_bench.py aggregate them."""
        tracer = self.tracer
        if tracer is None:
            return
        tp = st.headers.get("traceparent")
        trace = getattr(source, "trace", None)
        if isinstance(trace, dict):
            first_put = trace.get("first_put")
            if first_put is not None and first_put <= got:
                tracer.record_span("grpc.handoff", first_put, got,
                                   traceparent=tp,
                                   attributes={"stream": st.id})
        if "enc0" in stages:
            tracer.record_span("grpc.hpack", stages["enc0"], stages["enc1"],
                               traceparent=tp,
                               attributes={"stream": st.id})
        if "write0" in stages:
            tracer.record_span("grpc.frame-write", stages["write0"],
                               stages["write1"], traceparent=tp,
                               attributes={"stream": st.id})

    def _finish(self, conn: _Connection, st: _Stream, status: int,
                message: str, retry_after: float | None = None) -> None:
        try:
            trailers = [("grpc-status", str(status))]
            if message:
                trailers.append(("grpc-message",
                                 urllib.parse.quote(message, safe=" ")))
            if retry_after is not None:
                # shed/drain backpressure hint the client-side retry
                # policy reads before computing its own backoff
                from ..errors import format_retry_after

                trailers.append(("retry-after",
                                 format_retry_after(retry_after)))
            if not st.headers_sent:
                # trailers-only response
                trailers = _response_headers() + trailers
            conn.send_headers(st, trailers, end_stream=True)
        except (EOFError, OSError, h2.ConnectionError_):
            pass
        finally:
            conn.close_stream(st)


_RESPONSE_HEADERS = ((":status", "200"), ("content-type", "application/grpc"))


def _response_headers() -> list[tuple[str, str]]:
    return list(_RESPONSE_HEADERS)
