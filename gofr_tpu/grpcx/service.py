"""gRPC service model: method registry, codecs, status codes, handler context.

Reference: App.RegisterService (pkg/gofr/gofr.go:49-53) registers
protoc-generated servers on a grpc-go server. Here a service is declared
directly in Python — method name, handler, codec — and the transport
handles the wire. Two codecs:

  - JSON (default): request/response are dicts — the protoless path,
    symmetric with the HTTP responder envelope.
  - Protobuf: pass generated message classes (``request_type`` /
    ``response_type``); any standard ``*_pb2`` module works (the
    environment ships google.protobuf).

Unlike the reference (unary-only interceptors, grpc.go:22-26), methods may
be server-streaming — the handler returns/yields an iterator — which is
what token streaming needs (SURVEY §3.3 note).
"""

from __future__ import annotations

import json
from typing import Any, Callable

# gRPC status codes (subset used by the framework)
OK = 0
CANCELLED = 1
UNKNOWN = 2
INVALID_ARGUMENT = 3
DEADLINE_EXCEEDED = 4
NOT_FOUND = 5
PERMISSION_DENIED = 7
RESOURCE_EXHAUSTED = 8
UNIMPLEMENTED = 12
INTERNAL = 13
UNAVAILABLE = 14
UNAUTHENTICATED = 16

STATUS_NAMES = {
    0: "OK", 1: "CANCELLED", 2: "UNKNOWN", 3: "INVALID_ARGUMENT",
    4: "DEADLINE_EXCEEDED", 5: "NOT_FOUND", 7: "PERMISSION_DENIED",
    8: "RESOURCE_EXHAUSTED", 12: "UNIMPLEMENTED", 13: "INTERNAL",
    14: "UNAVAILABLE", 16: "UNAUTHENTICATED",
}

# One status vocabulary across both transports: a framework error raised
# with an HTTP status (errors.HTTPError subclasses — DeadlineExceeded 504,
# TooManyRequests 429, ServiceUnavailable 503, ...) maps to the
# equivalent gRPC code, so ``ctx.tpu.predict`` raising past its deadline
# is DEADLINE_EXCEEDED on gRPC and 504 on HTTP from the same exception.
HTTP_TO_GRPC_STATUS = {
    400: INVALID_ARGUMENT,
    401: UNAUTHENTICATED,
    403: PERMISSION_DENIED,
    404: NOT_FOUND,
    408: DEADLINE_EXCEEDED,
    429: RESOURCE_EXHAUSTED,
    499: CANCELLED,
    501: UNIMPLEMENTED,
    503: UNAVAILABLE,
    504: DEADLINE_EXCEEDED,
}


def from_http_error(e: BaseException) -> "GRPCError":
    """Bridge an errors.HTTPError-shaped exception into a GRPCError."""
    code = HTTP_TO_GRPC_STATUS.get(getattr(e, "status_code", 500), INTERNAL)
    return GRPCError(code, str(e) or STATUS_NAMES.get(code, str(code)))


def grpc_frame(payload: bytes) -> bytes:
    """gRPC length-prefixed message framing (RFC: compressed-flag byte,
    always 0 here, + u32 big-endian length). THE single definition —
    both transports' fast and fallback send paths must stay
    byte-compatible."""
    return b"\x00" + len(payload).to_bytes(4, "big") + payload


class GRPCError(Exception):
    """Raise from a handler to return a specific gRPC status."""

    def __init__(self, code: int, message: str = ""):
        super().__init__(message or STATUS_NAMES.get(code, str(code)))
        self.code = code
        self.message = message or STATUS_NAMES.get(code, str(code))


class JSONCodec:
    """dict <-> UTF-8 JSON bytes."""

    @staticmethod
    def serialize(obj: Any) -> bytes:
        return json.dumps(obj, default=str).encode()

    @staticmethod
    def deserialize(data: bytes) -> Any:
        return json.loads(data) if data else None


class ProtoCodec:
    """Codec over a generated protobuf message class."""

    def __init__(self, message_type):
        self.message_type = message_type

    def serialize(self, msg) -> bytes:
        return msg.SerializeToString()

    def deserialize(self, data: bytes):
        return self.message_type.FromString(data)


class Method:
    __slots__ = ("name", "handler", "request_codec", "response_codec",
                 "server_streaming", "client_streaming")

    def __init__(self, name: str, handler: Callable, request_codec,
                 response_codec, server_streaming: bool,
                 client_streaming: bool = False):
        self.name = name
        self.handler = handler
        self.request_codec = request_codec
        self.response_codec = response_codec
        self.server_streaming = server_streaming
        self.client_streaming = client_streaming


class ServerStream:
    """Server-streaming response wrapper that unlocks the transport's
    zero-handoff fast path.

    ``source`` is a push-capable stream — anything with the
    ``set_sink``/iterator protocol of ``gofr_tpu.wire.PushStream``
    (``GenStream`` qualifies) — and ``map_fn`` turns each item into the
    response message::

        @llm.server_stream("Generate")
        def generate(ctx, req):
            s = ctx.tpu.generate(req["tokens"], max_new_tokens=64)
            return ServerStream(s, lambda tok: {"token": tok})

    With a ServerStream the transport serializes and writes each token
    ON THE PRODUCING THREAD (no worker wakeup between the engine's
    ``_deliver`` and the socket); a plain generator handler keeps the
    classic pull path. Iterating a ServerStream degrades gracefully to
    the mapped items, so the same handler works when zero-handoff is
    disabled. ``close()`` is called by the transport when the RPC ends
    and cancels the source, releasing whatever it holds (engine slot)."""

    __slots__ = ("source", "map_fn")

    def __init__(self, source, map_fn: "Callable | None" = None):
        self.source = source
        self.map_fn = map_fn or (lambda item: item)

    def __iter__(self):
        for item in self.source:
            yield self.map_fn(item)

    def close(self) -> None:
        cancel = getattr(self.source, "cancel", None)
        if cancel is not None:
            cancel()

    @property
    def trace(self):
        """Delivery stamps of the source (GenStream sets first_put) —
        feeds the transport's grpc.handoff span."""
        return getattr(self.source, "trace", None)


class GRPCContext:
    """Per-RPC context handed to handlers: DI container access + metadata +
    deadline (richer than the reference, whose gRPC handlers bypass the
    gofr Context entirely — SURVEY §3.3)."""

    def __init__(self, container, method: str, metadata: dict[str, str],
                 deadline: float | None = None, peer: str = ""):
        self.container = container
        self.method = method
        self.metadata = metadata
        self.deadline = deadline  # monotonic deadline or None
        self.peer = peer
        self.cancelled = None  # threading.Event set on RST_STREAM

    @property
    def logger(self):
        return self.container.logger if self.container else None

    @property
    def tpu(self):
        return self.container.tpu if self.container else None

    @property
    def redis(self):
        return self.container.redis if self.container else None

    @property
    def sql(self):
        return self.container.sql if self.container else None

    def get_http_service(self, name: str):
        return self.container.get_http_service(name) if self.container else None

    def is_cancelled(self) -> bool:
        return self.cancelled is not None and self.cancelled.is_set()


class GRPCService:
    """A named service with registered methods.

    svc = GRPCService("demo.Echo")

    @svc.unary("Say")
    def say(ctx, req): return {"msg": req["msg"]}

    @svc.server_stream("Tokens", request_type=Req, response_type=Tok)
    def tokens(ctx, req):
        for t in ...: yield t
    """

    def __init__(self, name: str):
        if not name:
            raise ValueError("service name required")
        self.name = name
        self.methods: dict[str, Method] = {}

    def _codecs(self, request_type, response_type):
        req = ProtoCodec(request_type) if request_type is not None else JSONCodec()
        res = ProtoCodec(response_type) if response_type is not None else JSONCodec()
        return req, res

    def _register(self, name: str, fn: Callable, request_type, response_type,
                  streaming: bool, client_streaming: bool = False):
        req_c, res_c = self._codecs(request_type, response_type)
        self.methods[name] = Method(name, fn, req_c, res_c, streaming,
                                    client_streaming)
        return fn

    def _decorator(self, name, fn, request_type, response_type,
                   server_streaming, client_streaming):
        if fn is None:
            return lambda f: self._register(name, f, request_type,
                                            response_type, server_streaming,
                                            client_streaming)
        return self._register(name, fn, request_type, response_type,
                              server_streaming, client_streaming)

    def unary(self, name: str, fn: Callable | None = None, *,
              request_type=None, response_type=None):
        return self._decorator(name, fn, request_type, response_type,
                               False, False)

    def server_stream(self, name: str, fn: Callable | None = None, *,
                      request_type=None, response_type=None):
        return self._decorator(name, fn, request_type, response_type,
                               True, False)

    def client_stream(self, name: str, fn: Callable | None = None, *,
                      request_type=None, response_type=None):
        """handler(ctx, request_iterator) -> single response. The iterator
        yields deserialized messages as the client sends them and ends at
        the client's half-close."""
        return self._decorator(name, fn, request_type, response_type,
                               False, True)

    def bidi_stream(self, name: str, fn: Callable | None = None, *,
                    request_type=None, response_type=None):
        """handler(ctx, request_iterator) -> yields responses. Requests and
        responses interleave freely on one stream — the shape for
        incremental prompts / cancellable token generation."""
        return self._decorator(name, fn, request_type, response_type,
                               True, True)

    def lookup(self, method: str) -> Method | None:
        return self.methods.get(method)
