"""grpcx: native gRPC over an in-tree HTTP/2 + HPACK wire layer.

Reference: pkg/gofr/grpc.go (server, grpc-go based) and grpc/log.go
(logging interceptor). The environment ships no grpc runtime, so the
transport is part of the framework — which is also what lets it support
server streaming (needed for token streaming; the reference is unary-only,
SURVEY §3.3).

Public surface:
  GRPCService / GRPCError / status codes  — declare services
  GRPCServer                              — app-run transport (app.py wires it)
  GRPCChannel / dial                      — client side
  JSONCodec / ProtoCodec                  — message codecs
"""

from .client import BidiCall, GRPCChannel, dial
from .http2 import TransportOptions
from .server import GRPCServer
from .service import (CANCELLED, DEADLINE_EXCEEDED, GRPCContext, GRPCError,
                      GRPCService, INTERNAL, INVALID_ARGUMENT, JSONCodec,
                      NOT_FOUND, OK, ProtoCodec, RESOURCE_EXHAUSTED,
                      STATUS_NAMES, ServerStream, UNAUTHENTICATED,
                      UNAVAILABLE, UNIMPLEMENTED, UNKNOWN)

__all__ = [
    "BidiCall", "GRPCChannel", "dial", "GRPCServer", "ServerStream",
    "TransportOptions",
    "GRPCContext", "GRPCError", "GRPCService", "JSONCodec", "ProtoCodec",
    "STATUS_NAMES", "OK", "CANCELLED", "UNKNOWN", "INVALID_ARGUMENT",
    "DEADLINE_EXCEEDED", "NOT_FOUND", "RESOURCE_EXHAUSTED", "UNIMPLEMENTED",
    "INTERNAL", "UNAVAILABLE", "UNAUTHENTICATED",
]
