#!/usr/bin/env python
"""Durable-streams resume benchmark: token-exact recovery from
replica SIGKILL mid-stream.

REAL processes: N replica Apps (tiny llama engine, prefix cache on,
the canonical ``gofr_tpu.serving.install_generate`` route) behind a
gateway App with auto-resume on. The parent streams S concurrent
sessions through the gateway and SIGKILLs the session-0 affinity
owner once at least one token of every stream is in flight — the
in-flight relays lose their sockets mid-stream and the gateway must
splice continuations from the survivor. CPU-only (JAX_PLATFORMS=cpu);
the structural gates are the point.

Arms and gates (all STRICT):

  kill rounds   R rounds x S greedy sessions + 1 seeded SAMPLED
                session, each streaming max_new tokens while the
                affinity owner is SIGKILLed mid-stream, then
                respawned: ZERO client-visible errors (no typed error
                lines, no transport exceptions — the commit point is
                the stream end now), every stream token-exact vs its
                uninterrupted direct-to-replica reference (sampled
                included: resume re-keys the PRNG on absolute
                position), >= 1 gateway resume observed per round.
  warm resume   both replicas pre-warmed on every session's chain
                before each round, so the survivor admits the
                continuation from its prefix cache: the relayed
                continuation's ``recompute`` (prompt+emitted
                positions actually prefilled) <= one cache-block
                chunk of the chain tail, never the whole prompt.

Output follows the bench stdout contract (tools/README.md): the LAST
stdout line is the JSON artifact; progress goes to stderr. Full runs
write RESUME_BENCH.json.
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("TPU_TIMELINE", "0")

SEED_VOCAB = 500
BLOCK = 16
PROMPT_LEN = 40         # >= TPU_PREFIX_MIN: every session's chain stores
SAMPLED_SEED = 20180    # the pinned seed of the sampled session
RECOMPUTE_GATE = 2 * BLOCK  # warm resume recomputes only the chain tail


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


# -- child process: one serving replica ---------------------------------------

def run_replica(port: int) -> None:
    from gofr_tpu import App
    from gofr_tpu.config import MapConfig
    from gofr_tpu.serving import install_generate

    app = App(MapConfig({
        "APP_NAME": f"replica-{port}", "LOG_LEVEL": "ERROR",
        "HTTP_PORT": str(port), "METRICS_PORT": "0",
        "TPU_MODEL": "tiny", "TPU_MAX_SEQ": "256", "TPU_SLOTS": "4",
        "TPU_SEQ_BUCKETS": "32,64,96", "TPU_DECODE_BLOCK": "4",
        # Enough T0 slots for every session body plus the entries the live
        # streams store, and a T1 host tier underneath: an entry evicted
        # between the pre-warm and the kill must still resume WARM (the
        # warm_recompute_bounded gate is about resume warmth, not about
        # prefix-cache eviction pressure).
        "TPU_PREFIX_CACHE": "8", "TPU_PREFIX_MIN": "32",
        "TPU_KVCACHE_BLOCK": str(BLOCK), "TPU_KVCACHE_HOST_MB": "64",
        "TPU_WARMUP": "true",
    }))
    if app.container.tpu is None:
        print("ENGINE-FAILED", flush=True)
        return
    install_generate(app)
    app.run(block=False)
    print(f"READY {app.http_port}", flush=True)
    try:
        sys.stdin.read()  # parent closes stdin -> graceful drain
    except Exception:
        pass
    app.stop(grace_s=10.0)


class ReplicaProc:
    """Spawn/respawn handle for one replica child pinned to one port."""

    def __init__(self, port: int):
        self.port = port
        self.proc: subprocess.Popen | None = None

    @property
    def address(self) -> str:
        return f"127.0.0.1:{self.port}"

    def spawn(self) -> None:
        env = dict(os.environ, JAX_PLATFORMS="cpu", TPU_TIMELINE="0")
        self.proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--worker",
             "replica", "--port", str(self.port)],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE, env=env,
            text=True)

    def wait_ready(self, timeout_s: float = 180.0) -> None:
        assert self.proc is not None
        line = self.proc.stdout.readline().strip()
        if not line.startswith("READY "):
            raise RuntimeError(f"replica :{self.port} failed: {line!r}")
        # drain the child's stdout forever (wide events bypass the
        # log-level gate; an undrained pipe wedges the serving loop —
        # the gateway_bench lesson)
        out = self.proc.stdout
        threading.Thread(target=lambda: [None for _ in out],
                         name=f"drain-{self.port}", daemon=True).start()

    def drain_stop(self) -> None:
        if self.proc is not None:
            try:
                self.proc.stdin.close()
                self.proc.wait(timeout=60)
            except Exception:
                self.proc.kill()
            self.proc = None

    def kill(self) -> None:
        if self.proc is not None:
            self.proc.kill()
            self.proc.wait()
            self.proc = None


def free_ports(n: int) -> list[int]:
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def build_gateway(replica_addrs: list[str]):
    from gofr_tpu import App
    from gofr_tpu.config import MapConfig

    gw = App(MapConfig({
        "APP_NAME": "resume-bench-gw", "LOG_LEVEL": "ERROR",
        "HTTP_PORT": "0", "METRICS_PORT": "0",
        "TPU_SERVING_ROLE": "gateway",
        "TPU_GATEWAY_REPLICAS": ",".join(replica_addrs),
        "TPU_GATEWAY_BLOCK": str(BLOCK),
        "TPU_GATEWAY_HEALTH_INTERVAL_S": "0.5",
        "TPU_GATEWAY_CONNECT_TIMEOUT_S": "2.0",
    }))
    gw.run(block=False)
    return gw


def gw_stats(port: int) -> dict:
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/gateway/stats", timeout=10) as r:
        return json.loads(r.read())["data"]


# -- the client side ----------------------------------------------------------

def session_prompt(s: int) -> list[int]:
    return [(s * 131 + j) % SEED_VOCAB + 1 for j in range(PROMPT_LEN)]


def session_body(s: int, max_new: int, sampled: bool) -> dict:
    body = {"tokens": session_prompt(s), "max_new": max_new}
    if sampled:
        body.update(temperature=0.8, top_k=20, seed=SAMPLED_SEED)
    return body


def post_lines(port: int, body: dict, on_line=None,
               timeout: float = 120.0) -> list[dict]:
    """One streaming POST, parsed line by line (``on_line`` fires per
    parsed line — the kill trigger watches stream progress with it)."""
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/generate",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    lines = []
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        for raw in resp:
            raw = raw.strip()
            if not raw:
                continue
            obj = json.loads(raw)
            lines.append(obj)
            if on_line is not None:
                on_line(obj)
    return lines


class StreamRun:
    """One session's stream through the gateway on its own thread."""

    def __init__(self, gw_port: int, body: dict):
        self.body = body
        self.lines: list[dict] = []
        self.error: str | None = None
        self.first_token = threading.Event()
        self._thread = threading.Thread(target=self._run,
                                        args=(gw_port,), daemon=True)

    def _run(self, gw_port: int) -> None:
        try:
            self.lines = post_lines(
                gw_port, self.body,
                on_line=lambda obj: ("token" in obj
                                     and self.first_token.set()))
        except Exception as e:  # noqa: BLE001 — any escape is a gate fail
            self.error = repr(e)

    def start(self) -> None:
        self._thread.start()

    def join(self, timeout: float = 180.0) -> None:
        self._thread.join(timeout=timeout)
        if self._thread.is_alive() and self.error is None:
            self.error = "stream did not finish"

    @property
    def tokens(self) -> list[int]:
        return [ln["token"] for ln in self.lines if "token" in ln]

    @property
    def error_lines(self) -> list[dict]:
        return [ln for ln in self.lines if "error" in ln]

    @property
    def recomputes(self) -> list[int]:
        return [int(ln["recompute"]) for ln in self.lines
                if "recompute" in ln]


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--worker", choices=["replica"])
    ap.add_argument("--port", type=int, default=0)
    args = ap.parse_args()
    if args.worker == "replica":
        run_replica(args.port)
        return 0

    smoke = args.smoke
    rounds = 1 if smoke else 2
    greedy_sessions = 2 if smoke else 3
    max_new = 32 if smoke else 48

    payload: dict = {"bench": "resume", "smoke": smoke,
                     "rounds": rounds,
                     "sessions": greedy_sessions + 1,
                     "max_new": max_new,
                     "recompute_gate": RECOMPUTE_GATE}

    ports = free_ports(2)
    reps = [ReplicaProc(p) for p in ports]
    log(f"spawning 2 replicas on {ports}...")
    for r in reps:
        r.spawn()
    for r in reps:
        r.wait_ready()
    log("replicas ready")

    gw = build_gateway([r.address for r in reps])
    gw_port = gw.http_port

    from gofr_tpu.gateway import HashRing
    from gofr_tpu.tpu.kvcache import first_block_hash

    ring = HashRing([r.address for r in reps])

    bodies = [session_body(s, max_new, sampled=False)
              for s in range(greedy_sessions)]
    bodies.append(session_body(greedy_sessions, max_new, sampled=True))
    owners = [ring.order(first_block_hash(b["tokens"], BLOCK))[0]
              for b in bodies]
    # round 0 kills session 0's affinity owner; the last round kills
    # the SAMPLED session's owner, so the PRNG-re-keyed resume path is
    # exercised end to end whenever rounds >= 2
    victim_of_round = [owners[0], owners[-1]]

    try:
        # -- references: uninterrupted, direct to a replica ------------
        log("computing direct uninterrupted references...")
        refs = []
        for body in bodies:
            lines = post_lines(reps[0].port, dict(body))
            assert not any("error" in ln for ln in lines), lines
            refs.append([ln["token"] for ln in lines if "token" in ln])
        log(f"references: {len(refs)} streams x {max_new} tokens")

        round_results = []
        zero_errors = True
        token_exact = True
        recomputes_all: list[int] = []
        resumes_before = gw_stats(gw_port)["resumes"]

        for rnd in range(rounds):
            victim = victim_of_round[rnd % 2]
            # pre-warm BOTH replicas on every session chain: the
            # survivor must admit the continuation warm
            for body in bodies:
                for r in reps:
                    post_lines(r.port, dict(body))
            log(f"round {rnd}: chains pre-warmed; streaming "
                f"{len(bodies)} sessions, SIGKILL replica {victim} "
                "mid-stream...")
            runs = [StreamRun(gw_port, dict(body)) for body in bodies]
            for run in runs:
                run.start()
            # kill the instant every VICTIM-OWNED stream is committed
            # (>= 1 token relayed) — waiting on the others would let
            # fast streams finish before the kill lands mid-stream
            for i, run in enumerate(runs):
                if owners[i] != victim:
                    continue
                if not run.first_token.wait(timeout=60):
                    run.error = run.error or "no first token in 60s"
            reps[victim].kill()
            log(f"  replica {victim} KILLED")
            for run in runs:
                run.join()
            reps[victim].spawn()
            reps[victim].wait_ready()
            log(f"  replica {victim} respawned")
            time.sleep(1.0)  # the poller re-admits it

            rr = {"victim": victim, "streams": []}
            for i, run in enumerate(runs):
                exact = run.tokens == refs[i]
                errs = bool(run.error_lines) or run.error is not None
                rr["streams"].append({
                    "session": i,
                    "sampled": "seed" in bodies[i],
                    "tokens": len(run.tokens), "exact": exact,
                    "error_lines": len(run.error_lines),
                    "transport_error": run.error,
                    "recompute": run.recomputes})
                zero_errors = zero_errors and not errs
                token_exact = token_exact and exact
                recomputes_all.extend(run.recomputes)
            round_results.append(rr)
            log(f"  round {rnd}: exact={token_exact} "
                f"errors={not zero_errors} "
                f"recomputes={recomputes_all}")

        resumes = gw_stats(gw_port)["resumes"] - resumes_before
        payload["rounds_detail"] = round_results
        payload["resumes"] = resumes
        payload["recomputes"] = recomputes_all
        payload["gateway_stats"] = gw_stats(gw_port)
    finally:
        gw.stop()
        for r in reps:
            r.drain_stop()

    checks = {
        # the durable-streams promise: a mid-stream SIGKILL is
        # invisible — no typed error line, no transport exception
        "zero_client_errors": zero_errors,
        # splice exactness, greedy AND seeded-sampled sessions
        "token_exact": token_exact,
        # the kill landed mid-stream and the gateway resumed
        "resumes_observed": resumes >= rounds,
        # warm resume recomputes only the chain tail, never the prompt
        "warm_recompute_bounded":
            len(recomputes_all) >= 1
            and max(recomputes_all) <= RECOMPUTE_GATE,
    }
    payload["checks"] = checks
    payload["ok"] = all(checks.values())
    print(json.dumps(payload), flush=True)
    return 0 if payload["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
