"""Multi-chip tensor-parallel serving bench: tp scaling, token
exactness, and warm device-loss recovery.

The ROADMAP's multi-chip item gates on exactly this run: tensor-
parallel decode as a REAL serving configuration — sharded KV leased
per device from the HBM arbiter, mesh-aware paged attention, per-shard
T1 offload, and a mid-serving device loss that re-places the mesh and
resumes warm instead of dying (docs/advanced-guide/
multichip-serving.md).

Arms (each a fresh engine built from its TPU_* config rows, same keys
production serving reads):

  tp1          single-device contiguous engine — the reference stream
               every other arm must match token-for-token, and the
               scaling baseline.
  tp2 / tp4    mesh engines (``TPU_SHARDING=tp=N,dp=rest``): aggregate
               decode tok/s with every slot busy, token-exact vs tp1.
  tp2_paged    mesh-aware PAGED engine (block pool sharded over tp,
               dense-gather attention): token-exact vs tp1 — the
               paged+mesh composition this PR lifted the refusal on.
  device_loss  tp=2 engine with a prefix pool + T1 host tier: prime
               T0, spill to T1, then a seeded chaos ``GENERATOR_STEP``
               DeviceLost mid-serving. Gates: the in-flight stream
               fails TYPED (no process death), the mesh re-places
               (stats.mesh.replacements >= 1), the repeat prompt
               serves WARM from T1, post-recovery tokens are exact,
               and the arbiter's in-use figure re-settles to the
               pre-loss byte count (leases replaced, never
               double-counted).

STRUCTURAL gates are strict everywhere (exactness, recovery, per-shard
lease visibility, 0 deaths). The SCALING gate (aggregate tok/s up with
tp) is judged only on real multi-device hardware: on virtual CPU
devices (this container: 8-way ``jax_num_cpu_devices``) every "chip"
time-slices one host, so tp adds partitioning overhead with zero added
FLOPs — the ratio is recorded advisory, the same caveat class
slo_bench documents.

Conventions (tools/README.md): the LAST stdout line is the JSON
artifact; ``--smoke`` is the CI gate (smaller shapes, same structural
invariants); full runs commit ``MULTICHIP_SERVE_BENCH.json``. Exit is
non-zero only when a strict gate fails.
"""

from __future__ import annotations

import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def log(*a) -> None:
    print(*a, file=sys.stderr, flush=True)


def _init_devices() -> int:
    """CPU: fan the host platform out to 8 virtual devices BEFORE
    first backend use (the tests/conftest.py recipe); TPU: use the
    slice as-is."""
    import jax

    if not os.environ.get("GOFR_BENCH_TPU"):
        jax.config.update("jax_platforms", "cpu")
        try:
            jax.config.update("jax_num_cpu_devices", 8)
        except AttributeError:
            flags = os.environ.get("XLA_FLAGS", "")
            if "--xla_force_host_platform_device_count" not in flags:
                os.environ["XLA_FLAGS"] = (
                    flags + " --xla_force_host_platform_device_count=8"
                ).strip()
        jax.config.update("jax_default_matmul_precision", "float32")
    return jax.device_count()


def _build(cfg, params, rows: dict):
    """Engine from TPU_* rows — bench.engine_from_rows, so an arm
    definition IS a deployable serving config."""
    import bench

    return bench.engine_from_rows(cfg, params, rows)


def _drive(engine, cfg, *, streams: int, new_tokens: int,
           prompt_len: int = 16) -> dict:
    """Two phases. THROUGHPUT: fill every slot, wall-clock all tokens
    out (aggregate decode tok/s through the full serving stack).
    EXACTNESS: fixed greedy prompts served ONE AT A TIME — the regime
    tests/test_sharded_serving.py proves bit-stable across tp
    factorizations (a fully-batched probe would gate on fp reduction
    order across different activation shardings, which no tp change
    preserves — a numerics artifact, not a sharding bug)."""
    import numpy as np

    rng = np.random.default_rng(11)
    prompts = [rng.integers(1, cfg.vocab_size, prompt_len).tolist()
               for _ in range(streams)]
    t0 = time.perf_counter()
    outs = [engine.generate(p, max_new_tokens=new_tokens) for p in prompts]
    total = sum(len(s.tokens()) for s in outs)
    dt = time.perf_counter() - t0
    probes = [[5, 17, 42, 7, 9, 3, 11, 2],
              list(range(2, 18)),
              [31, 4, 15, 9, 2, 6]]
    probe_toks = [engine.generate(p, max_new_tokens=new_tokens).tokens()
                  for p in probes]
    return {"tok_s": round(total / dt, 1), "tokens": total,
            "seconds": round(dt, 2), "streams": probe_toks}


def run(smoke: bool) -> dict:
    n_dev = _init_devices()
    import jax

    from gofr_tpu import chaos
    from gofr_tpu.models.common import LLAMA_CONFIGS
    from gofr_tpu.tpu import GenerationError, hbm
    from gofr_tpu.tpu.kvcache import KVCacheOptions
    import bench

    platform = jax.devices()[0].platform
    # full-precision weights + model-dtype cache: the exactness gate
    # judges the SHARDING machinery (specs, collectives, masked row
    # copies), and fp weights make greedy argmax invariant across tp
    # factorizations (the proven test_sharded_serving regime). int8
    # weight quantization re-orders the dequant psum reductions per tp
    # and can flip a borderline argmax — a numerics artifact the int8
    # config documents, not a sharding bug. The model must fit ONE
    # chip (the tp1 reference arm) and every tp arm must DIVIDE its
    # n_kv_heads — splitting a KV head on a multi-axis mesh is the
    # documented wrong-logits hazard this bench's bring-up found
    # (multichip-serving.md "known limits"), so the CPU config widens
    # tiny to 4 KV heads (MHA) to keep tp=4 in the clean regime.
    cfg = (LLAMA_CONFIGS["tiny"].with_(n_kv_heads=4)
           if platform == "cpu" else LLAMA_CONFIGS["llama-1b"])
    slots = 4 if smoke else 8
    new_tokens = 12 if smoke else 48
    from gofr_tpu.models import llama

    params = llama.init(cfg, jax.random.PRNGKey(0))
    base = {"TPU_SLOTS": str(slots), "TPU_MAX_SEQ": "128",
            "TPU_SEQ_BUCKETS": "32", "TPU_KV_DTYPE": "model",
            "TPU_DECODE_BLOCK": "4"}

    def mesh_spec(tp: int) -> str:
        dp = n_dev // tp
        return f"tp={tp}" + (f",dp={dp}" if dp > 1 else "")

    arm_rows = [("tp1", dict(base))]
    for tp in (2, 4):
        if n_dev >= tp and n_dev % tp == 0:
            arm_rows.append((f"tp{tp}",
                             {**base, "TPU_SHARDING": mesh_spec(tp)}))
    if n_dev >= 2 and n_dev % 2 == 0:
        arm_rows.append(("tp2_paged",
                         {**base, "TPU_SHARDING": mesh_spec(2),
                          "TPU_PAGED_BLOCKS": str(slots * 5 + 1),
                          "TPU_PAGED_BLOCK": "32"}))

    arms: dict[str, dict] = {}
    ref_streams = None
    sharded_lease_devices: set[str] = set()
    for name, rows in arm_rows:
        extra = {k: v for k, v in rows.items() if k not in base}
        log(f"arm {name}: rows={extra or 'base'}")
        engine = None
        try:
            engine = _build(cfg, params, rows)
            res = _drive(engine, cfg, streams=slots, new_tokens=new_tokens)
            streams = res.pop("streams")
            if name == "tp1":
                ref_streams = streams
            exact = streams == ref_streams
            arm = {"status": "ok", "token_exact_vs_tp1": exact, **res}
            if engine.mesh is not None:
                arm["mesh"] = engine.stats()["mesh"]
                for row in hbm.arbiter_stats()["leases"]:
                    if "device" in row:
                        sharded_lease_devices.add(row["device"])
            arms[name] = arm
            log(f"  {name}: {res['tok_s']} tok/s aggregate, "
                f"exact={exact}")
        except Exception as e:  # noqa: BLE001 — each arm reports its fate
            arms[name] = {"status": "error",
                          "error": f"{type(e).__name__}: {str(e)[:200]}"}
            log(f"  {name} FAILED: {arms[name]['error']}")
        finally:
            if engine is not None:
                engine.close()

    # -- the device-loss arm --------------------------------------------------
    loss = {"status": "error"}
    engine = None
    # built directly rather than via _build/engine_from_rows: the T1
    # host tier is a constructor option outside the perf-arm row set
    try:
        import jax.numpy as jnp

        from gofr_tpu.parallel import make_mesh, shard_params
        from gofr_tpu.tpu import GenerationEngine

        mesh = None
        mparams = params
        if n_dev >= 2 and n_dev % 2 == 0:
            mesh = make_mesh(tp=2, dp=n_dev // 2)
            mparams = shard_params(params, mesh)
        engine = GenerationEngine(
            cfg, mparams, mesh=mesh, slots=slots, max_seq=128,
            prompt_buckets=(32,), kv_dtype=jnp.int8, decode_block=4,
            prefix_cache_slots=1, prefix_store_min=16,
            kvcache=KVCacheOptions(host_mb=64))
        pA = list(range(1, 33))
        ref = engine.generate(pA + [1, 2], max_new_tokens=8).tokens()
        engine.generate(list(range(40, 72)) + [3, 4],
                        max_new_tokens=8).tokens()  # spill A's row to T1
        in_use_before = hbm.arbiter_stats()["in_use_bytes"]
        sched = chaos.ChaosSchedule(seed=7).on(
            chaos.GENERATOR_STEP, error=chaos.DeviceLost, every=1, limit=1)
        typed_failure = False
        with chaos.scope(sched):
            try:
                engine.generate([9, 8, 7, 6], max_new_tokens=8).tokens()
            except GenerationError:
                typed_failure = True  # the SHED contract: typed, not a death
        s2 = engine.generate(pA + [1, 2], max_new_tokens=8)
        got = s2.tokens()
        st = engine.stats()
        in_use_after = hbm.arbiter_stats()["in_use_bytes"]
        loss = {
            "status": "ok",
            "typed_failure": typed_failure,
            "replacements": (st.get("mesh", {}).get("replacements", 0)
                             if mesh is not None else engine._recoveries),
            "post_recovery_exact": got == ref,
            "warm_tier": s2.cache_tier,
            "engine_down": engine.down is not None,
            "in_use_before": in_use_before,
            "in_use_after": in_use_after,
            "leases_resettled": in_use_before == in_use_after,
        }
        log(f"  device_loss: typed={typed_failure} "
            f"replacements={loss['replacements']} warm={s2.cache_tier} "
            f"exact={loss['post_recovery_exact']} "
            f"resettled={loss['leases_resettled']}")
    except Exception as e:  # noqa: BLE001
        loss = {"status": "error",
                "error": f"{type(e).__name__}: {str(e)[:200]}"}
        log(f"  device_loss FAILED: {loss['error']}")
    finally:
        if engine is not None:
            engine.close()

    # -- gates ----------------------------------------------------------------
    mesh_arms = [n for n in arms if n != "tp1"]
    scaling = {}
    if "tp1" in arms and arms["tp1"].get("status") == "ok":
        for n in ("tp2", "tp4"):
            if arms.get(n, {}).get("status") == "ok":
                scaling[f"{n}_vs_tp1"] = round(
                    arms[n]["tok_s"] / arms["tp1"]["tok_s"], 3)
    scaling_gated = platform != "cpu" and n_dev > 1
    checks = {
        "all_arms_ok": all(a.get("status") == "ok" for a in arms.values()),
        "mesh_arms_present": len(mesh_arms) >= 2,
        "all_token_exact": all(a.get("token_exact_vs_tp1")
                               for a in arms.values()
                               if a.get("status") == "ok"),
        "per_shard_leases_visible": len(sharded_lease_devices) >= 2,
        "loss_arm_recovered_warm": (
            loss.get("status") == "ok" and loss.get("typed_failure")
            and loss.get("post_recovery_exact")
            and loss.get("warm_tier") == "t1"
            and not loss.get("engine_down")
            and loss.get("replacements", 0) >= 1
            and loss.get("leases_resettled")),
        "zero_deaths": True,  # we are here emitting the artifact
    }
    if scaling_gated:
        # real hardware: tp must buy aggregate throughput
        checks["scaling_up"] = all(v > 1.1 for v in scaling.values()) \
            and bool(scaling)
    ok = all(checks.values())
    return {
        "bench": "multichip_serve",
        "smoke": smoke,
        "ok": ok,
        "platform": platform,
        "devices": n_dev,
        "arms": arms,
        "device_loss": loss,
        "scaling": scaling,
        "scaling_gate": ("strict" if scaling_gated
                         else "advisory (virtual devices time-slice one "
                              "host: tp adds partitioning overhead with "
                              "zero added FLOPs)"),
        "checks": checks,
        "sharded_lease_devices": sorted(sharded_lease_devices),
    }


def main() -> None:
    smoke = "--smoke" in sys.argv[1:]
    out = run(smoke)
    print(json.dumps(out))
    sys.exit(0 if out["ok"] else 1)


if __name__ == "__main__":
    main()
