#!/usr/bin/env python
"""Overload/chaos benchmark: proves the admission gate keeps goodput at
capacity and admitted-request p99 flat when offered load is 2x capacity.

Hardware-independent (CPU backend; tiny identity program, no chip lock):
service time is pinned DETERMINISTICALLY by the chaos harness — a
``ChaosSchedule`` latency rule on the ``batcher.dispatch`` seam makes
every device dispatch cost exactly ``--service-ms`` — so capacity is
known by construction::

    capacity = max_batch / service_time   (items per second)

Three phases drive ``TPUEngine.predict`` open-loop (arrivals on a fixed
seeded schedule, one thread per in-flight request):

  baseline           0.5x capacity, admission gate on — the healthy
                     latency reference
  overload           2x capacity, admission gate on — excess requests
                     shed fast with 429-class ``TooManyRequests``;
                     admitted requests keep near-baseline latency and
                     goodput holds at capacity
  overload_ungated   2x capacity, NO gate, per-request deadlines only —
                     the contrast arm: the queue grows, waits blow past
                     the deadline, and the dispatcher drops expired
                     items unexecuted (``app_tpu_expired_dropped_total``)

Acceptance (full runs; RESILIENCE_BENCH.json):
  - overload admitted p99 <= 1.5x baseline p99
  - overload goodput within 10% of capacity
  - shed rejects are fast: p50 < 5 ms
  - ungated arm proves deadline enforcement: expired drops > 0

Output follows the bench stdout contract (tools/README.md): the LAST
stdout line is the JSON artifact; earlier lines are progress snapshots
carrying a "partial" marker. Full runs write ``--out``
(RESILIENCE_BENCH.json); ``--smoke`` (the CI mode) runs a reduced
schedule, skips the file, and exits non-zero only if harness
INVARIANTS break (every request accounted for exactly once, sheds
present under overload and absent at baseline, deterministic schedule
digest). Run it twice and diff ``schedule_digest`` to prove the seeded
schedule replays identically.
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def pctl(vals, p):
    if not vals:
        return None
    vs = sorted(vals)
    return vs[min(len(vs) - 1, int(p / 100.0 * len(vs)))]


class Phase:
    """Open-loop load: one request at each scheduled offset, each on its
    own thread; outcomes are tallied exactly once."""

    def __init__(self, name: str, engine, rate_rps: float, duration_s: float,
                 deadline_s: float | None):
        self.name = name
        self.engine = engine
        self.rate = rate_rps
        self.duration = duration_s
        self.deadline_s = deadline_s
        self.lock = threading.Lock()
        self.completed: list[float] = []   # latency seconds
        self.shed: list[float] = []        # reject latency seconds
        self.expired: list[float] = []     # deadline-drop latency seconds
        self.errors: list[str] = []

    def _one(self, item) -> None:
        from gofr_tpu.errors import DeadlineExceeded, TooManyRequests
        from gofr_tpu.resilience import Deadline

        dl = (Deadline.after(self.deadline_s)
              if self.deadline_s is not None else None)
        t0 = time.monotonic()
        try:
            self.engine.predict("echo", item, timeout=10.0, deadline=dl)
            out, dt = self.completed, time.monotonic() - t0
        except TooManyRequests:
            out, dt = self.shed, time.monotonic() - t0
        except DeadlineExceeded:
            out, dt = self.expired, time.monotonic() - t0
        except Exception as e:  # noqa: BLE001 — tally, judge later
            with self.lock:
                self.errors.append(repr(e))
            return
        with self.lock:
            out.append(dt)

    def run(self) -> dict:
        import numpy as np

        item = np.arange(1, 7, dtype=np.int32)
        n = int(self.rate * self.duration)
        interval = 1.0 / self.rate
        threads = []
        t_start = time.monotonic()
        for i in range(n):
            target = t_start + i * interval
            pause = target - time.monotonic()
            if pause > 0:
                time.sleep(pause)
            t = threading.Thread(target=self._one, args=(item,), daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout=30.0)
        wall = time.monotonic() - t_start
        return {
            "offered_rps": round(self.rate, 1),
            "offered": n,
            "completed": len(self.completed),
            "goodput_rps": round(len(self.completed) / wall, 1),
            "p50_ms": round((pctl(self.completed, 50) or 0) * 1e3, 2),
            "p99_ms": round((pctl(self.completed, 99) or 0) * 1e3, 2),
            "sheds": len(self.shed),
            "shed_p50_ms": round((pctl(self.shed, 50) or 0) * 1e3, 3),
            "expired": len(self.expired),
            "errors": len(self.errors),
            "wall_s": round(wall, 2),
        }


def calibrate(engine, max_batch: int, seconds: float) -> float:
    """Measured capacity: closed-loop saturation (2*max_batch workers,
    no gate) for ``seconds``. The theoretical max_batch/service_time
    ignores real harness overhead — sleep overshoot under GIL load,
    dispatch turnaround — so offered rates and the goodput check are
    anchored to what this box can actually complete per second."""
    import numpy as np

    item = np.arange(1, 7, dtype=np.int32)
    stop = time.monotonic() + seconds
    counts = [0] * (2 * max_batch)

    def worker(i: int) -> None:
        while time.monotonic() < stop:
            engine.predict("echo", item, timeout=10.0)
            counts[i] += 1

    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(len(counts))]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=seconds + 10.0)
    return sum(counts) / (time.monotonic() - t0)


def build_engine(service_s: float, max_batch: int, gate):
    """Tiny identity program; the chaos latency rule IS the service time."""
    from gofr_tpu.tpu.engine import TPUEngine

    eng = TPUEngine(max_delay=0.002, model_name="chaos-bench", gate=gate)

    def echo_fn(params, tokens, lengths):
        return tokens

    eng.register("echo", echo_fn, params=None, kind="tokens",
                 batch_buckets=tuple(sorted({1, 2, max_batch})),
                 seq_buckets=(8,))
    # warm every (batch, seq) bucket OUTSIDE the chaos scope: a mid-phase
    # XLA compile would masquerade as queue delay and trip the gate
    eng.warmup("echo")
    return eng


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seed", type=int, default=0)
    # 40 ms service keeps offered thread rates low (<= ~200/s at 2x):
    # the harness is Python threads, and spawning much faster than that
    # turns GIL scheduling into the bottleneck being measured
    ap.add_argument("--service-ms", type=float, default=40.0,
                    help="injected per-dispatch service time")
    ap.add_argument("--max-batch", type=int, default=4)
    ap.add_argument("--baseline-s", type=float, default=6.0)
    ap.add_argument("--overload-s", type=float, default=6.0)
    ap.add_argument("--ungated-s", type=float, default=2.5)
    ap.add_argument("--out", default=str(Path(__file__).resolve().parent.parent
                                         / "RESILIENCE_BENCH.json"))
    ap.add_argument("--smoke", action="store_true",
                    help="reduced CI run: invariants only, no artifact file")
    args = ap.parse_args()

    if args.smoke:
        args.baseline_s, args.overload_s, args.ungated_s = 2.0, 2.5, 1.6

    from gofr_tpu import chaos
    from gofr_tpu.resilience import AdmissionGate

    service_s = args.service_ms / 1e3
    schedule = chaos.ChaosSchedule(seed=args.seed).on(
        chaos.BATCHER_DISPATCH, latency=service_s)
    digest = schedule.digest()
    log(f"theoretical capacity={args.max_batch / service_s:.0f} rps "
        f"(batch {args.max_batch} / {args.service_ms}ms), "
        f"schedule digest {digest[:12]}")

    # calibration: measure what THIS box completes per second saturated
    # (engines always build + warm OUTSIDE the chaos scope: warmup must
    # neither pay injected latency nor consume seam call indices)
    engine = build_engine(service_s, args.max_batch, gate=None)
    try:
        with chaos.scope(schedule):
            capacity = calibrate(engine, args.max_batch,
                                 1.5 if args.smoke else 3.0)
    finally:
        engine.close()
    log(f"measured capacity={capacity:.0f} rps")

    result = {
        "bench": "chaos_resilience",
        "seed": args.seed,
        "smoke": bool(args.smoke),
        "service_ms": args.service_ms,
        "max_batch": args.max_batch,
        "capacity_rps_theoretical": round(args.max_batch / service_s, 1),
        "capacity_rps": round(capacity, 1),
        "schedule_digest": digest,
    }

    # gated arms: the shed boundary is ONE full batch of queued work —
    # deep enough that the dispatcher always finds a full batch waiting
    # (goodput = capacity), shallow enough that an admitted request
    # waits at most (rest of current dispatch + its own batch)
    gate = AdmissionGate(max_queue_depth=args.max_batch, name="predict")
    engine = build_engine(service_s, args.max_batch, gate)
    try:
        with chaos.scope(schedule):
            ph = Phase("baseline", engine, 0.5 * capacity, args.baseline_s,
                       deadline_s=2.0)
            result["baseline"] = ph.run()
            print(json.dumps({"partial": "overload pending", **result}),
                  flush=True)
            ph = Phase("overload", engine, 2.0 * capacity, args.overload_s,
                       deadline_s=2.0)
            result["overload"] = ph.run()
    finally:
        engine.close()
    # contrast arm: no gate — only per-request deadlines bound the wait
    print(json.dumps({"partial": "ungated pending", **result}),
          flush=True)
    engine = build_engine(service_s, args.max_batch, gate=None)
    try:
        with chaos.scope(schedule):
            ph = Phase("overload_ungated", engine, 2.0 * capacity,
                       args.ungated_s, deadline_s=6 * service_s)
            result["overload_ungated"] = ph.run()
    finally:
        engine.close()

    base, over, ungated = (result["baseline"], result["overload"],
                           result["overload_ungated"])
    p99_ratio = (over["p99_ms"] / base["p99_ms"]) if base["p99_ms"] else None
    goodput_ratio = over["goodput_rps"] / capacity
    result["checks"] = {
        "p99_ratio_vs_baseline": round(p99_ratio, 3) if p99_ratio else None,
        "p99_within_1p5x": bool(p99_ratio is not None and p99_ratio <= 1.5),
        "goodput_ratio_vs_capacity": round(goodput_ratio, 3),
        "goodput_within_10pct": bool(goodput_ratio >= 0.9),
        "shed_p50_under_5ms": bool(over["sheds"] > 0
                                   and over["shed_p50_ms"] < 5.0),
        "ungated_expired_drops": ungated["expired"],
    }

    # harness invariants (both modes): every request accounted exactly once,
    # the gate sheds under overload and not at baseline, no stray errors
    invariants = []
    for name in ("baseline", "overload", "overload_ungated"):
        ph_r = result[name]
        total = (ph_r["completed"] + ph_r["sheds"] + ph_r["expired"]
                 + ph_r["errors"])
        if total != ph_r["offered"]:
            invariants.append(f"{name}: {total} accounted != "
                              f"{ph_r['offered']} offered")
        if ph_r["errors"]:
            invariants.append(f"{name}: {ph_r['errors']} unexpected errors")
    if base["sheds"] > 0.02 * base["offered"]:
        # open-loop spawn jitter can brush the depth cap; more than 2%
        # shed at half load means the gate boundary is wrong
        invariants.append(f"baseline shed {base['sheds']}/{base['offered']} "
                          "at 0.5x load")
    if not over["sheds"]:
        invariants.append("overload produced no sheds at 2x load")
    if ungated["expired"] == 0:
        invariants.append("ungated overload dropped no expired items")
    if schedule.digest() != digest:
        invariants.append("schedule digest changed mid-run")
    result["invariants_failed"] = invariants

    ok = not invariants
    if not args.smoke:
        # acceptance thresholds only on full runs — smoke boxes are noisy
        ok = ok and all(v for k, v in result["checks"].items()
                        if isinstance(v, bool))
        Path(args.out).write_text(json.dumps(result, indent=1) + "\n")
        log(f"wrote {args.out}")
    print(json.dumps(result), flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
