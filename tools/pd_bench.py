#!/usr/bin/env python
"""Disaggregated prefill/decode two-process benchmark: fused vs
P/D-split under the SLO_BENCH mixed prefill-heavy load shape.

Two REAL processes: this parent runs the client side — a fused
baseline engine, then a prefill worker (``PDPrefill``) — and a child
process runs the decode worker (``KVIngestServer``); KV crosses a real
localhost socket as checksummed int8 block frames. CPU-only
(JAX_PLATFORMS=cpu, tiny model): the point is the RATIO between the
fused and split topologies on identical hardware.

Load shape — the two phases' TRAFFIC CLASSES run side by side (the
mixed prefill-heavy SLO_BENCH shape, made explicit):

  - STEADY decode: long-lived decode-bound streams (short prompt,
    hundreds of tokens) — the memory-bound phase. Their decode BLOCK
    cadence p99 is gated: on the fused chip every arriving prefill's
    chunk stalls the whole batch for the chunk's duration (bounded by
    the PR 7 interleave, but each stall is a full chunk+block); the
    split decode pool never dispatches a prefill chunk at all.
  - BURSTY prefill: LONG_LEN-token prompts with short tails arriving
    continuously — the compute-bound phase. Their TTFT p50 is gated:
    the fused engine makes each one (a) wait for a decode SLOT in the
    shared pool and (b) interleave one decode block for the live
    batch after every chunk; the split prefill pool's slots recycle
    instantly (prefill-only requests hold a slot for one prefill) and
    no decode block ever runs between its chunks. The first token is
    delivered FROM the prefill worker (it sampled it), so the KV
    handoff is off the TTFT critical path entirely.
  - short latency-class probes ride along for reference and drive the
    kill/recovery arm (not perf-gated: PR 7's interleave + latency
    slot reserve already hold short-probe TTFT at the floor
    in-process).

Kill/recovery arm (the acceptance criterion's hard part): mid-run the
decode child is SIGKILLed and respawned. In-flight relays surface as
TYPED sheds (503 + Retry-After) which the client retries honoring
Retry-After — the gate is ZERO non-shed failures across the whole run,
the prefill worker never dies, and post-recovery output is token-exact.

Output follows the bench stdout contract (tools/README.md): the LAST
stdout line is the JSON artifact; progress goes to stderr. Full runs
write PD_BENCH.json.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import subprocess
import sys
import threading
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("TPU_TIMELINE", "0")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from gofr_tpu.errors import TooManyRequests  # noqa: E402
from gofr_tpu.models import LLAMA_CONFIGS, llama  # noqa: E402
from gofr_tpu.pd import (DecodePeerUnavailable, KVIngestServer,  # noqa: E402
                         PDPrefill)
from gofr_tpu.resilience import SLO_THROUGHPUT  # noqa: E402
from gofr_tpu.tpu import GenerationEngine  # noqa: E402
from gofr_tpu.tpu.kvcache import model_fingerprint  # noqa: E402


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def pctl(vals, p):
    if not vals:
        return None
    vs = sorted(vals)
    return vs[min(len(vs) - 1, int(p / 100.0 * len(vs)))]


SEED = 11
MAX_SEQ = 512
BUCKETS = (8, 16, 32)
LONG_LEN = 480
SHORT_LEN = 6
DECODE_BLOCK = 4
EXACT_PROMPT_LEN = 40


def harness_cfg():
    return dataclasses.replace(LLAMA_CONFIGS["tiny"], max_seq=MAX_SEQ)


def build_engine(slots: int = 4):
    cfg = harness_cfg()
    params = llama.init(cfg, jax.random.PRNGKey(SEED))
    eng = GenerationEngine(cfg, params, slots=slots, max_seq=MAX_SEQ,
                           prompt_buckets=BUCKETS, kv_dtype=jnp.int8,
                           decode_block=DECODE_BLOCK)
    eng.warmup()
    return cfg, params, eng


def prompts_rng():
    return np.random.default_rng(42)


# -- child process: the decode worker ----------------------------------------

def run_decode_worker(port: int) -> None:
    cfg, params, eng = build_engine(slots=4)
    fp = model_fingerprint(cfg, params, extra="pd")
    srv = KVIngestServer(eng, fp, "127.0.0.1", port)
    print(f"READY {srv.port}", flush=True)
    try:
        # serve until the parent closes our stdin (clean shutdown) or
        # kills us (the recovery arm)
        sys.stdin.read()
    except Exception:
        pass
    srv.close()
    eng.close()


class DecodeChild:
    """Spawn/respawn handle for the decode worker process."""

    def __init__(self):
        self.proc = None
        self.port = 0

    def spawn(self) -> int:
        env = dict(os.environ, JAX_PLATFORMS="cpu", TPU_TIMELINE="0")
        self.proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--worker",
             "decode", "--port", "0"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE, env=env,
            text=True)
        line = self.proc.stdout.readline().strip()
        if not line.startswith("READY "):
            raise RuntimeError(f"decode worker failed to start: {line!r}")
        self.port = int(line.split()[1])
        return self.port

    def kill(self) -> None:
        if self.proc is not None:
            self.proc.kill()
            self.proc.wait()
            self.proc = None

    def stop(self) -> None:
        if self.proc is not None:
            try:
                self.proc.stdin.close()
                self.proc.wait(timeout=10)
            except Exception:
                self.proc.kill()
            self.proc = None


# -- the mixed-load driver ----------------------------------------------------

class Counts:
    def __init__(self):
        self.ok = 0
        self.sheds = 0
        self.failures = 0
        self.fail_reprs: list[str] = []
        self.lock = threading.Lock()

    def shed(self):
        with self.lock:
            self.sheds += 1

    def good(self):
        with self.lock:
            self.ok += 1

    def fail(self, e: BaseException):
        with self.lock:
            self.failures += 1
            if len(self.fail_reprs) < 8:
                self.fail_reprs.append(repr(e))


def _retry_after_of(e: BaseException) -> float:
    return float(getattr(e, "retry_after", None) or 0.3)


class Background:
    """Closed-loop stream pool: ``steady`` decode-bound streams (their
    BLOCK cadence is recorded) plus ``bursty`` long-prompt short-tail
    streams (their TTFT is recorded). Typed sheds (429/503) retry
    honoring Retry-After — the zero-non-shed-failures gate counts
    everything else."""

    def __init__(self, submit, counts: Counts, *, steady: int,
                 steady_new: int, bursty: int, bursty_new: int):
        self.submit = submit
        self.counts = counts
        self.gaps: list[float] = []
        self.ttfts: list[float] = []
        self.lock = threading.Lock()
        self.stop = threading.Event()
        rng = prompts_rng()
        cfg = harness_cfg()
        specs = []
        for _ in range(steady):
            specs.append((rng.integers(1, cfg.vocab_size,
                                       SHORT_LEN * 2).tolist(),
                          steady_new, SLO_THROUGHPUT, True, False))
        for _ in range(bursty):
            specs.append((rng.integers(1, cfg.vocab_size,
                                       LONG_LEN).tolist(),
                          bursty_new, None, False, True))
        self.threads = [threading.Thread(target=self._run, args=spec,
                                         daemon=True)
                        for spec in specs]

    def _run(self, prompt, max_new, slo, rec_gaps, rec_ttft) -> None:
        while not self.stop.is_set():
            try:
                t0 = time.monotonic()
                s = self.submit(prompt, max_new, slo)
                i, t_block = 0, None
                for _ in s:
                    i += 1
                    if i == 1 and rec_ttft:
                        with self.lock:
                            self.ttfts.append(time.monotonic() - t0)
                    if rec_gaps and i % DECODE_BLOCK == 0:
                        now = time.monotonic()
                        if t_block is not None:
                            with self.lock:
                                self.gaps.append(now - t_block)
                        t_block = now
                    if self.stop.is_set():
                        s.cancel()
                        break
                self.counts.good()
            except (TooManyRequests, DecodePeerUnavailable) as e:
                self.counts.shed()
                self.stop.wait(_retry_after_of(e))
            except Exception as e:  # noqa: BLE001 — the gate counts these
                self.counts.fail(e)
                self.stop.wait(0.2)

    def start(self):
        for t in self.threads:
            t.start()

    def finish(self):
        self.stop.set()
        for t in self.threads:
            t.join(timeout=30)
        return list(self.gaps), list(self.ttfts)


def probe_loop(submit, n_probes: int, spacing_s: float, probe_new: int,
               counts: Counts, deadline_s: float = 60.0) -> list[float]:
    """Latency-class probes on a fixed cadence; a shed probe retries
    (honoring Retry-After) until served or the per-probe deadline —
    the recovery arm's probes ride decode-worker downtime this way."""
    rng = prompts_rng()
    cfg = harness_cfg()
    ttfts: list[float] = []
    for _ in range(n_probes):
        prompt = rng.integers(1, cfg.vocab_size, SHORT_LEN).tolist()
        t_end = time.monotonic() + deadline_s
        while True:
            t0 = time.monotonic()
            try:
                s = submit(prompt, probe_new, None)
                it = iter(s)
                next(it)
                ttfts.append(time.monotonic() - t0)
                for _ in it:
                    pass
                counts.good()
                break
            except (TooManyRequests, DecodePeerUnavailable) as e:
                counts.shed()
                if time.monotonic() >= t_end:
                    counts.fail(RuntimeError(
                        "probe still shed at its retry deadline"))
                    break
                time.sleep(min(_retry_after_of(e), 1.0))
            except StopIteration:
                counts.fail(RuntimeError("probe stream ended tokenless"))
                break
            except Exception as e:  # noqa: BLE001
                counts.fail(e)
                break
        time.sleep(spacing_s)
    return ttfts


def measure_arm(submit, *, load_kw: dict, probes: int,
                spacing_s: float, probe_new: int) -> dict:
    counts = Counts()
    load = Background(submit, counts, **load_kw)
    load.start()
    time.sleep(0.5)  # let the phases start colliding
    probe_ttfts = probe_loop(submit, probes, spacing_s, probe_new, counts)
    gaps, ttfts = load.finish()
    return {
        # TTFT of the prefill-bound traffic — the gated number
        "ttft_ms": {"p50": round((pctl(ttfts, 50) or 0) * 1e3, 2),
                    "p95": round((pctl(ttfts, 95) or 0) * 1e3, 2),
                    "n": len(ttfts)},
        "probe_ttft_ms": {
            "p50": round((pctl(probe_ttfts, 50) or 0) * 1e3, 2),
            "p95": round((pctl(probe_ttfts, 95) or 0) * 1e3, 2),
            "n": len(probe_ttfts)},
        "block_gap_ms": {"p50": round((pctl(gaps, 50) or 0) * 1e3, 2),
                         "p99": round((pctl(gaps, 99) or 0) * 1e3, 2),
                         "n": len(gaps)},
        "ok": counts.ok, "sheds": counts.sheds,
        "failures": counts.failures, "failure_reprs": counts.fail_reprs,
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--worker", choices=["decode"])
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--json", default=None)
    args = ap.parse_args()
    if args.worker == "decode":
        run_decode_worker(args.port)
        return 0

    smoke = args.smoke
    # mixed prefill-heavy load: a steady decode-bound pool (cadence-
    # gated) colliding with continuous long-prompt arrivals (TTFT-
    # gated) — each phase is the other's hazard on a fused chip
    load_kw = {"steady": 3, "steady_new": 384,
               "bursty": 2, "bursty_new": 8}
    probes, probe_new = (14, 4) if smoke else (24, 4)
    spacing = 1.0
    kill_probes = 6 if smoke else 10

    rng = prompts_rng()
    cfg = harness_cfg()
    exact_prompt = rng.integers(1, cfg.vocab_size,
                                EXACT_PROMPT_LEN).tolist()

    payload: dict = {"bench": "pd_split", "smoke": smoke,
                     "load": {**load_kw, "probes": probes,
                              "long_len": LONG_LEN}}

    # -- arm 1: fused baseline (one process, both phases) -------------
    log("building fused baseline engine...")
    _, _, fused = build_engine(slots=4)
    fused_submit = lambda p, n, slo: fused.generate(  # noqa: E731
        p, max_new_tokens=n, slo_class=slo)
    exact_ref = fused.generate(exact_prompt, max_new_tokens=12).tokens()
    log("measuring fused arm...")
    payload["fused"] = measure_arm(fused_submit, load_kw=load_kw,
                                   probes=probes, spacing_s=spacing,
                                   probe_new=probe_new)
    fused.close()
    log(f"fused: {payload['fused']}")

    # -- arm 2: P/D split (decode worker in a child process) ----------
    log("spawning decode worker child...")
    child = DecodeChild()
    child.spawn()
    log(f"decode worker ready on :{child.port}; building prefill worker...")
    pcfg, pparams, pre = build_engine(slots=4)
    fp = model_fingerprint(pcfg, pparams, extra="pd")
    pd = PDPrefill(pre, fp, "127.0.0.1", child.port, ship_block=16)
    pd_submit = lambda p, n, slo: pd.generate(  # noqa: E731
        p, max_new_tokens=n, slo_class=slo)
    exact_split = pd.generate(exact_prompt, max_new_tokens=12).tokens()
    payload["exact_tokens"] = exact_split == exact_ref
    log(f"split exactness vs fused: {payload['exact_tokens']}")
    log("measuring split arm...")
    payload["split"] = measure_arm(pd_submit, load_kw=load_kw,
                                   probes=probes, spacing_s=spacing,
                                   probe_new=probe_new)
    log(f"split: {payload['split']}")
    print(json.dumps({**payload, "partial": "kill arm pending"}),
          flush=True)

    # -- arm 3: kill + recovery mid-run -------------------------------
    log("kill/recovery arm: SIGKILL the decode worker mid-run...")
    counts = Counts()
    load = Background(pd_submit, counts, **load_kw)
    load.start()
    time.sleep(0.5)
    killer_done = threading.Event()

    def killer():
        time.sleep(spacing * 2)
        child.kill()
        log("decode worker KILLED; prefill worker must keep serving")
        time.sleep(1.0)
        child.spawn()
        pd.peer = ("127.0.0.1", child.port)
        log(f"decode worker RESPAWNED on :{child.port}")
        killer_done.set()

    threading.Thread(target=killer, daemon=True).start()
    ttfts_kill = probe_loop(pd_submit, kill_probes, spacing, probe_new,
                            counts, deadline_s=90.0)
    killer_done.wait(timeout=60)
    load.finish()
    post = pd.generate(exact_prompt, max_new_tokens=12).tokens()
    payload["kill_arm"] = {
        "probes_served": len(ttfts_kill),
        "ok": counts.ok, "sheds": counts.sheds,
        "failures": counts.failures, "failure_reprs": counts.fail_reprs,
        "prefill_worker_alive": pre.down is None,
        "post_recovery_exact": post == exact_ref,
        "peer_losses": pd.stats()["peer_losses"],
    }
    log(f"kill arm: {payload['kill_arm']}")

    pd.close()
    child.stop()
    pre.close()

    f, s = payload["fused"], payload["split"]
    # The perf criterion needs hardware that can EXPRESS two pools: on
    # a multi-core host the decode child owns a core, so its cadence
    # is the clean block and the prefill worker's chunks run without
    # interleaved decode blocks — both metrics beat fused. On a
    # SINGLE-core host the two processes time-slice one CPU
    # preemptively while the fused engine multiplexes the same core
    # cooperatively; split then does strictly more total work with
    # zero added parallelism and the perf comparison measures the OS
    # scheduler, not the architecture (the same hardware caveat
    # slo_bench documents for its CPU p99 ratio). Perf gates are
    # therefore STRICT with >= 2 cores and advisory-recorded on 1;
    # the structural gates (exactness, zero non-shed failures,
    # kill/recovery) are strict everywhere.
    try:
        cores = len(os.sched_getaffinity(0))
    except AttributeError:
        cores = os.cpu_count() or 1
    perf_gated = cores >= 2
    perf_checks = {
        "split_ttft_p50_beats_fused":
            s["ttft_ms"]["p50"] < f["ttft_ms"]["p50"],
        "split_block_gap_p99_beats_fused":
            s["block_gap_ms"]["p99"] < f["block_gap_ms"]["p99"],
    }
    structural_checks = {
        "exact_tokens": bool(payload["exact_tokens"]),
        "zero_nonshed_failures":
            f["failures"] == 0 and s["failures"] == 0
            and payload["kill_arm"]["failures"] == 0,
        "kill_arm_recovered":
            payload["kill_arm"]["prefill_worker_alive"]
            and payload["kill_arm"]["post_recovery_exact"]
            and payload["kill_arm"]["probes_served"] == kill_probes
            and payload["kill_arm"]["peer_losses"] >= 1,
    }
    payload["checks"] = {**structural_checks, **perf_checks}
    payload["cores"] = cores
    payload["perf_gated"] = perf_gated
    payload["ttft_improvement_pct"] = round(
        100.0 * (1 - s["ttft_ms"]["p50"] / max(f["ttft_ms"]["p50"], 1e-9)),
        1)
    payload["gap_p99_ratio"] = round(
        s["block_gap_ms"]["p99"] / max(f["block_gap_ms"]["p99"], 1e-9), 3)
    gates = dict(structural_checks)
    if perf_gated:
        gates.update(perf_checks)
    payload["ok"] = all(gates.values())
    if args.json or not smoke:
        out = Path(args.json or Path(__file__).resolve().parent.parent
                   / "PD_BENCH.json")
        out.write_text(json.dumps(payload, indent=2) + "\n")
        log(f"wrote {out}")
    print(json.dumps(payload), flush=True)
    return 0 if payload["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
