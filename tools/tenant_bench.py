#!/usr/bin/env python
"""Multi-tenant serving plane benchmark: weighted fair share, quota
shed isolation, per-tenant cache budgets, and the async lane's
kill/resume — the four contracts of gofr_tpu/tenancy under real load.

CPU-only (JAX_PLATFORMS=cpu, tiny model, no chip lock): every check is
a RATIO or an exactness claim on identical hardware, never an absolute
chip number. Four arms, one process, one run:

ARM 1 — weighted fair share at ~2x saturation:
  three tenants (weights 2:1:1) each keep enough closed-loop drivers
  alive that the pending line always holds every tenant (~2x the slot
  count outstanding). The DRR line must hand tenant A twice the decode
  tokens of B or C: each tenant's steady-state token share must land
  within +/-15% (relative) of its weight share.

ARM 2 — quota shed isolation:
  tenants A and B run an uncontended open-loop phase (the reference
  tail), then re-run at the same rates while tenant "capped" (rps
  quota far below its offered rate) hammers the same engine. The
  quota must shed ONLY the capped tenant (typed 429,
  reason=tenant_quota, Retry-After set), A/B must shed zero, and
  their TTFT tail must hold: p95 within max(1.3x, +50 ms noise
  floor) of uncontended (the same CPU-jitter rationale as
  slo_bench's overload gate; the raw ratio is recorded).

ARM 3 — per-tenant cache budgets:
  tenants A and B each hold a 0.5 share of a small T0 prefix pool.
  Both warm their budgets; then A floods with new prefixes. Every
  eviction must come out of A's own rows — B's resident rows and its
  re-query hit must survive untouched.

ARM 4 — async lane kill/resume:
  a bulk job dies mid-run after 3 tokens (worker crash), leaving a
  Redis checkpoint; the redelivered job must resume via
  continue_from and finish TOKEN-EXACT against the uninterrupted
  greedy reference.

Output follows the bench stdout contract (tools/README.md): the LAST
stdout line is the JSON artifact; progress goes to stderr. Full runs
write TENANT_BENCH.json on a green run.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import threading
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402
import numpy as np  # noqa: E402

from gofr_tpu.errors import TooManyRequests  # noqa: E402
from gofr_tpu.models import LLAMA_CONFIGS, llama  # noqa: E402
from gofr_tpu.tenancy import (AsyncLane, TenantPlane,  # noqa: E402
                              TenantRegistry, tenant_scope)
from gofr_tpu.tpu import GenerationEngine  # noqa: E402


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def pctl(vals, p):
    if not vals:
        return None
    vs = sorted(vals)
    return vs[min(len(vs) - 1, int(p / 100.0 * len(vs)))]


BUCKETS = (8, 16, 32)
MAX_SEQ = 256
SLOTS = 4


class Harness:
    def __init__(self):
        self.cfg = dataclasses.replace(LLAMA_CONFIGS["tiny"],
                                       max_seq=MAX_SEQ)
        self.params = llama.init(self.cfg, jax.random.PRNGKey(1))
        self.rng = np.random.default_rng(42)

    def engine(self, doc=None, **kw) -> GenerationEngine:
        kw.setdefault("slots", SLOTS)
        kw.setdefault("max_seq", MAX_SEQ)
        kw.setdefault("prompt_buckets", BUCKETS)
        kw.setdefault("decode_block", 2)
        eng = GenerationEngine(self.cfg, self.params, **kw)
        if doc is not None:
            eng.install_tenancy(TenantPlane(TenantRegistry.from_json(doc)))
        eng.warmup()
        return eng

    def prompt(self, n: int):
        return self.rng.integers(1, self.cfg.vocab_size, n).tolist()


# -- ARM 1: weighted fair share ----------------------------------------------

FAIR_DOC = {"tenants": [{"id": "A", "weight": 2},
                        {"id": "B", "weight": 1},
                        {"id": "C", "weight": 1}]}


def run_fairness(h: Harness, duration: float) -> dict:
    log("tenant_bench: fairness: building engine")
    eng = h.engine(FAIR_DOC)
    tokens = {"A": 0, "B": 0, "C": 0}
    lock = threading.Lock()
    stop = threading.Event()
    warm = threading.Event()  # count only steady-state tokens

    def drive(tenant: str) -> None:
        while not stop.is_set():
            try:
                with tenant_scope(tenant):
                    stream = eng.generate(h.prompt(16), max_new_tokens=8)
                n = len(stream.tokens())
            except Exception:
                time.sleep(0.01)
                continue
            if warm.is_set():
                with lock:
                    tokens[tenant] += n

    # 3 drivers per tenant vs 4 slots: the pending line always holds
    # every tenant (~2x saturation) so DRR — not arrival luck — picks
    threads = [threading.Thread(target=drive, args=(t,), daemon=True)
               for t in ("A", "B", "C") for _ in range(3)]
    try:
        for t in threads:
            t.start()
        time.sleep(min(2.0, duration / 4))  # warmup: fill the line
        warm.set()
        time.sleep(duration)
        stop.set()
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30.0)
        eng.close()
    total = sum(tokens.values()) or 1
    weights = {"A": 2, "B": 1, "C": 1}
    wsum = sum(weights.values())
    shares, errs = {}, {}
    for t, w in weights.items():
        shares[t] = tokens[t] / total
        expect = w / wsum
        errs[t] = abs(shares[t] / expect - 1.0)
    out = {
        "tokens": tokens,
        "shares": {t: round(s, 4) for t, s in shares.items()},
        "expected": {t: round(w / wsum, 4) for t, w in weights.items()},
        "rel_err": {t: round(e, 4) for t, e in errs.items()},
        "within_15pct": bool(max(errs.values()) <= 0.15),
    }
    log(f"tenant_bench: fairness: {out}")
    return out


# -- ARM 2: quota shed isolation ----------------------------------------------

QUOTA_DOC = {"tenants": [{"id": "A", "weight": 1},
                         {"id": "B", "weight": 1},
                         {"id": "capped", "weight": 1, "rps": 2.0}]}


class Phase:
    """Open-loop per-tenant load from a fixed worker pool (the
    slo_bench Phase pattern: pool, not thread-per-request, so spawn
    jitter stays out of the tails)."""

    WORKERS = 24

    def __init__(self, h: Harness, eng, rates: dict, duration: float):
        self.h = h
        self.eng = eng
        self.rates = rates
        self.duration = duration
        self.lock = threading.Lock()
        self.ttft = {t: [] for t in rates}
        self.sheds = {t: 0 for t in rates}
        self.mistyped = 0  # tenant sheds missing the reason/Retry-After
        self.errors: list[str] = []

    def _one(self, tenant: str) -> None:
        try:
            with tenant_scope(tenant):
                stream = self.eng.generate(self.h.prompt(6),
                                           max_new_tokens=4)
            stream.tokens()
            t = stream.trace["first_put"] - stream.trace["submit"]
        except TooManyRequests as e:
            with self.lock:
                self.sheds[tenant] += 1
                if getattr(e, "reason", None) != "tenant_quota" \
                        or not getattr(e, "retry_after", None):
                    self.mistyped += 1
            return
        except Exception as e:  # noqa: BLE001 — tally, judge later
            with self.lock:
                self.errors.append(repr(e))
            return
        with self.lock:
            self.ttft[tenant].append(t)

    def run(self) -> dict:
        arrivals = []
        for tenant, rate in self.rates.items():
            if rate <= 0:
                continue
            n = max(1, int(rate * self.duration))
            arrivals += [(i / rate, tenant) for i in range(n)]
        arrivals.sort()
        cursor = [0]
        t0 = time.monotonic()

        def worker() -> None:
            while True:
                with self.lock:
                    i = cursor[0]
                    if i >= len(arrivals):
                        return
                    cursor[0] = i + 1
                offset, tenant = arrivals[i]
                pause = t0 + offset - time.monotonic()
                if pause > 0:
                    time.sleep(pause)
                self._one(tenant)

        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(min(self.WORKERS, len(arrivals)))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=self.duration + 60.0)
        out = {"offered": len(arrivals), "errors": len(self.errors),
               "mistyped_sheds": self.mistyped}
        for tenant in self.rates:
            out[tenant] = {
                "completed": len(self.ttft[tenant]),
                "sheds": self.sheds[tenant],
                "ttft_p50_ms": round((pctl(self.ttft[tenant], 50) or 0)
                                     * 1e3, 2),
                "ttft_p95_ms": round((pctl(self.ttft[tenant], 95) or 0)
                                     * 1e3, 2),
                "ttft_p99_ms": round((pctl(self.ttft[tenant], 99) or 0)
                                     * 1e3, 2),
            }
        return out


def run_quota(h: Harness, duration: float) -> dict:
    log("tenant_bench: quota: building engine")
    eng = h.engine(QUOTA_DOC)
    try:
        base_rate = 2.0  # well inside a 4-slot tiny engine's capacity
        uncontended = Phase(h, eng, {"A": base_rate, "B": base_rate},
                            duration).run()
        contended = Phase(h, eng, {"A": base_rate, "B": base_rate,
                                   "capped": 15.0}, duration).run()
        plane_stats = eng.tenancy.stats()["tenants"]
    finally:
        eng.close()
    unc_p95 = max(uncontended[t]["ttft_p95_ms"] for t in ("A", "B"))
    over_p95 = max(contended[t]["ttft_p95_ms"] for t in ("A", "B"))
    bound_ms = max(1.3 * unc_p95, unc_p95 + 50.0) if unc_p95 else None
    out = {
        "uncontended": uncontended,
        "contended": contended,
        "capped_plane_sheds": plane_stats["capped"]["shed"],
        "checks": {
            "capped_shed": bool(contended["capped"]["sheds"] > 0),
            "sheds_typed_tenant_quota": contended["mistyped_sheds"] == 0,
            "others_never_shed": (contended["A"]["sheds"] == 0
                                  and contended["B"]["sheds"] == 0),
            "tail_gate": "p95 vs max(1.3x, +50ms floor)",
            "others_p95_ms": over_p95,
            "others_p95_bound_ms": (round(bound_ms, 2)
                                    if bound_ms else None),
            "others_tail_holds": bool(bound_ms is not None
                                      and over_p95 <= bound_ms),
            "p95_ratio": (round(over_p95 / unc_p95, 3)
                          if unc_p95 else None),
        },
    }
    log(f"tenant_bench: quota: {out['checks']}")
    return out


# -- ARM 3: per-tenant cache budgets ------------------------------------------

CACHE_DOC = {"tenants": [{"id": "A", "weight": 1, "cache_share": 0.5},
                         {"id": "B", "weight": 1, "cache_share": 0.5}]}


def run_cache(h: Harness) -> dict:
    log("tenant_bench: cache: building engine")
    eng = h.engine(CACHE_DOC, prefix_cache_slots=4, prefix_store_min=8)
    rng = np.random.default_rng(7)

    def gen(tenant, prompt):
        with tenant_scope(tenant):
            stream = eng.generate(prompt, max_new_tokens=2)
        stream.tokens()
        return stream

    try:
        b_prompts = [rng.integers(1, h.cfg.vocab_size, 16).tolist()
                     for _ in range(2)]
        for p in b_prompts:
            gen("B", p)  # B warms its full budget (2 rows)
        rows_after_warm = dict(eng._kvc.tenant_rows())
        evictions_before = eng._kvc.t0.evictions
        # A floods: 4 distinct prefixes through a 2-row budget
        for _ in range(4):
            gen("A", rng.integers(1, h.cfg.vocab_size, 16).tolist())
        rows_after_flood = dict(eng._kvc.tenant_rows())
        evictions = eng._kvc.t0.evictions - evictions_before
        # B's working set must still be warm: a re-query hits T0
        hits_before = eng._kvc.hits
        s = gen("B", b_prompts[0])
        b_hit = eng._kvc.hits > hits_before and s.cache_tokens > 0
        budget = eng._kvc.tenant_budget("A")
    finally:
        eng.close()
    out = {
        "t0_slots": 4,
        "budget_rows": budget,
        "rows_after_warm": rows_after_warm,
        "rows_after_flood": rows_after_flood,
        "a_evictions": evictions,
        "b_requery_hit": bool(b_hit),
        "checks": {
            "a_stays_at_budget": rows_after_flood.get("A", 0) <= budget,
            "b_rows_untouched": (rows_after_flood.get("B", 0)
                                 == rows_after_warm.get("B", 0)),
            "a_evicted_itself": evictions >= 2,
            "b_requery_hit": bool(b_hit),
        },
    }
    log(f"tenant_bench: cache: {out['checks']}")
    return out


# -- ARM 4: async lane kill/resume --------------------------------------------

class _Store:
    def __init__(self):
        self.kv = {}

    def get(self, key):
        return self.kv.get(key)

    def set(self, key, value, ex=None):
        self.kv[key] = value
        return True


class _Ctx:
    def __init__(self, payload, tpu, redis):
        self._payload = payload
        self.tpu = tpu
        self.redis = redis

    def bind(self):
        return self._payload


class _KillAfter:
    def __init__(self, engine, n):
        self.engine = engine
        self.n = n

    def generate(self, *a, **kw):
        stream = self.engine.generate(*a, **kw)

        def die():
            for i, item in enumerate(stream):
                if i >= self.n:
                    stream.cancel()
                    raise RuntimeError("worker died mid-run")
                yield item
        return die()


def run_lane(h: Harness) -> dict:
    log("tenant_bench: lane: building engine")
    eng = h.engine({"tenants": [{"id": "bulk", "weight": 1}]})
    store = _Store()
    prompt = h.prompt(8)
    job = {"job_id": "bench", "tokens": prompt, "max_new": 8,
           "tenant": "bulk"}
    try:
        ref = eng.generate(prompt, max_new_tokens=8).tokens()
        lane = AsyncLane(checkpoint_every=2)
        died = False
        try:
            lane.handle(_Ctx(job, _KillAfter(eng, 3), store))
        except RuntimeError:
            died = True
        ckpt = json.loads(store.kv["async:bench"])
        lane.handle(_Ctx(job, eng, store))  # the redelivery
        doc = json.loads(store.kv["async:bench"])
    finally:
        eng.close()
    out = {
        "reference_tokens": len(ref),
        "died_mid_run": died,
        "checkpoint_tokens": len(ckpt.get("tokens", ())),
        "checkpoint_status": ckpt.get("status"),
        "final_status": doc.get("status"),
        "lane": lane.stats(),
        "checks": {
            "killed_after_checkpoint": bool(
                died and ckpt.get("status") == "running"
                and ckpt.get("tokens") == [int(t) for t in ref[:3]]),
            "resume_token_exact": doc.get("tokens")
            == [int(t) for t in ref],
            "marked_done": doc.get("status") == "done",
            "counted_resumed": lane.stats()["resumed"] == 1,
        },
    }
    log(f"tenant_bench: lane: {out['checks']}")
    return out


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--fairness-s", type=float, default=12.0)
    ap.add_argument("--quota-s", type=float, default=8.0)
    ap.add_argument("--out", default=str(Path(__file__).resolve()
                                         .parent.parent
                                         / "TENANT_BENCH.json"))
    ap.add_argument("--smoke", action="store_true",
                    help="reduced CI run: no artifact file")
    args = ap.parse_args()
    if args.smoke:
        args.fairness_s, args.quota_s = 6.0, 5.0

    h = Harness()
    result = {"bench": "tenant_plane", "smoke": bool(args.smoke),
              "slots": SLOTS, "buckets": list(BUCKETS)}

    result["fairness"] = run_fairness(h, args.fairness_s)
    print(json.dumps({"partial": "quota pending", **result}), flush=True)
    result["quota"] = run_quota(h, args.quota_s)
    print(json.dumps({"partial": "cache pending", **result}), flush=True)
    result["cache"] = run_cache(h)
    result["lane"] = run_lane(h)

    invariants = []
    if sum(result["fairness"]["tokens"].values()) == 0:
        invariants.append("fairness: no tokens decoded")
    for phase in ("uncontended", "contended"):
        if result["quota"][phase]["errors"]:
            invariants.append(
                f"quota/{phase}: {result['quota'][phase]['errors']} "
                "errors")
    if result["quota"]["uncontended"]["A"]["sheds"] \
            or result["quota"]["uncontended"]["B"]["sheds"]:
        invariants.append("quota: uncontended phase shed traffic")
    result["invariants_failed"] = invariants

    checks_ok = all((
        result["fairness"]["within_15pct"],
        result["quota"]["checks"]["capped_shed"],
        result["quota"]["checks"]["sheds_typed_tenant_quota"],
        result["quota"]["checks"]["others_never_shed"],
        result["quota"]["checks"]["others_tail_holds"],
        all(result["cache"]["checks"].values()),
        all(result["lane"]["checks"].values()),
    ))
    ok = not invariants and checks_ok
    result["ok"] = ok
    if not args.smoke and ok:
        Path(args.out).write_text(json.dumps(result, indent=1) + "\n")
        log(f"wrote {args.out}")
    print(json.dumps(result), flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
