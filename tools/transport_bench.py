#!/usr/bin/env python
"""Loopback microbenchmark for the gRPC transport fast path.

Hardware-independent (CPU only, no jax import, no chip lock needed):
the token source is a producer thread feeding a ``wire.PushStream`` at
decode cadence, so the own-wire transport cost — HPACK encode, frame
writes, window updates, thread handoffs — is isolated from the engine.
This is the regression gate for ISSUE 2's ~142 ms gRPC TTFT tax: the
"before" arm runs ``TransportOptions.legacy()`` (the pre-fast-path wire
behavior), the "after" arm runs the default fast options, both in one
invocation, so the win is re-provable on any box every round.

Measured per arm:
  - ``unary_rps``                 echo round-trips per second
  - ``stream_first_byte_ms_p50``  client-observed first-message latency
                                  on a server stream (the transport
                                  slice of TTFT)
  - ``syscalls_per_token``        (server + client write syscalls) /
                                  tokens delivered on a long stream
  - ``frames_per_syscall``        server frames per write syscall
  - ``hpack_encode_ns``           ns per header-block encode
  - ``headers_with_first_data``   True when HEADERS+first-DATA left in
                                  one vectored write
  - ``stage_p50_ms``              grpc.hpack / grpc.frame-write /
                                  grpc.handoff span medians (the TTFT
                                  decomposition the tracer exports)

Output follows the bench stdout contract (tools/README.md): the LAST
stdout line is the JSON artifact; earlier lines are progress. The
artifact is also written to ``--out`` (default TRANSPORT_BENCH.json
next to the repo root) unless ``--smoke``.

``--smoke`` (the CI mode) runs a reduced iteration count and exits
non-zero if the harness invariants break: streamed tokens must arrive
complete and in order, the fast arm must coalesce HEADERS with the
first DATA frame, and both arms must agree on results.
"""

from __future__ import annotations

import argparse
import json
import statistics
import sys
import threading
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from gofr_tpu.grpcx import (GRPCServer, GRPCService, ServerStream,  # noqa: E402
                            TransportOptions, dial)
from gofr_tpu.grpcx import hpack  # noqa: E402
from gofr_tpu.tracing import InMemoryExporter, Tracer  # noqa: E402
from gofr_tpu.wire import PushStream  # noqa: E402


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


class _TracedStream(PushStream):
    """PushStream stamping first_put like GenStream does, so the
    transport's grpc.handoff span has its start mark."""

    def __init__(self):
        super().__init__()
        self.trace: dict[str, float] = {}

    def push(self, item) -> None:
        if "first_put" not in self.trace:
            self.trace["first_put"] = time.monotonic()
        self._push(item)


class _Shim:
    """Container stand-in giving the server a tracer + span capture."""

    def __init__(self):
        self.logger = None
        self.exporter = InMemoryExporter()
        self.tracer = Tracer(service_name="transport-bench",
                             exporter=self.exporter)


class _Producer:
    """ONE long-lived delivery thread for all streams — the shape of the
    engine's serving loop (tpu/generator._loop), which delivers tokens
    for every request from a single thread that is already running when
    a request arrives. A thread-per-request producer would charge both
    arms a thread-spawn on the first-byte path the real engine never
    pays."""

    def __init__(self):
        import queue

        self.jobs: "queue.Queue" = queue.Queue()
        self._stop = False
        self._queue_mod = queue
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="bench-engine-loop")
        self._thread.start()

    def _loop(self):
        active: list[list] = []
        while not self._stop:
            # admit new streams (block only when idle) — like slot admission
            try:
                while True:
                    job = self.jobs.get(block=not active)
                    if job is None:
                        return
                    if job == ("clear",):
                        # abandoned streams (their clients closed): stop
                        # feeding dead queues for the rest of the arm
                        for j in active:
                            j[0].push(None)
                        active.clear()
                        continue
                    active.append([*job, 0])  # [src, count, pad, gap, i]
            except self._queue_mod.Empty:
                pass
            # one token per active stream per iteration (decode round)
            gap = 0.0
            for job in list(active):
                src, count, pad, gap_s, i = job
                src.push({"t": i, "pad": pad} if pad else {"t": i})
                job[4] = i + 1
                if job[4] >= count:
                    src.push(None)
                    active.remove(job)
                gap = max(gap, gap_s)
            if gap:
                time.sleep(gap)

    def clear(self):
        """Drop streams submitted so far (their consumers are gone)."""
        self.jobs.put(("clear",))

    def stop(self):
        self._stop = True
        self.jobs.put(None)


def _make_server(options: TransportOptions, n_tokens: int,
                 gap_s: float) -> tuple[GRPCServer, _Shim, _Producer]:
    svc = GRPCService("bench.Transport")
    producer = _Producer()

    @svc.unary("Echo")
    def echo(ctx, req):
        return req

    @svc.server_stream("Tokens")
    def tokens(ctx, req):
        src = _TracedStream()
        producer.jobs.put((src, int(req.get("n", n_tokens)),
                           "x" * int(req.get("pad", 0)), gap_s))
        return ServerStream(src)

    shim = _Shim()
    srv = GRPCServer([svc], port=0, container=shim, options=options)
    srv.start()
    return srv, shim, producer


def _io_stats(io) -> tuple[int, int]:
    return io.writer.syscalls, io.frames_sent


def run_arm(name: str, options: TransportOptions, *, unary_n: int,
            stream_iters: int, stream_tokens: int, gap_s: float) -> dict:
    srv, shim, producer = _make_server(options, stream_tokens, gap_s)
    ch = dial(f"127.0.0.1:{srv.port}", options=options)
    out: dict = {"arm": name}
    try:
        # warm the connection (SETTINGS exchange, first-stream costs)
        ch.unary("/bench.Transport/Echo", {"warm": 1})

        t0 = time.perf_counter()
        for i in range(unary_n):
            ch.unary("/bench.Transport/Echo", {"i": i})
        dt = time.perf_counter() - t0
        out["unary_rps"] = round(unary_n / dt, 1)

        # streaming first-byte latency: producer pushes token 0
        # immediately; the client measures call-start -> first message.
        # Probed WITH background token streams running — the same
        # convention as bench.bench_ttft ("while other slots are
        # decoding"): serving TTFT is never measured on an idle box,
        # and the wakeup/syscall tax under concurrency is exactly what
        # the fast path removes.
        bg_chs = [dial(f"127.0.0.1:{srv.port}", options=options)
                  for _ in range(2)]
        bg_threads = []

        def bg_pull(c):
            try:
                # finite but far longer than the probe window; killed by
                # close() below, and the producer stops at arm teardown
                for _ in c.server_stream("/bench.Transport/Tokens",
                                         {"n": 200_000},
                                         timeout=600.0):
                    pass
            except Exception:
                pass  # torn down by close() below

        for c in bg_chs:
            t = threading.Thread(target=bg_pull, args=(c,), daemon=True)
            t.start()
            bg_threads.append(t)
        time.sleep(0.2)  # let the background cadence reach steady state
        first_ms = []
        for _ in range(stream_iters):
            t0 = time.perf_counter()
            it = ch.server_stream("/bench.Transport/Tokens", {"n": 3})
            first = next(iter(it))
            first_ms.append((time.perf_counter() - t0) * 1e3)
            assert first["t"] == 0, f"first message out of order: {first}"
            for _ in it:
                pass
        for c in bg_chs:
            c.close()
        producer.clear()  # stop feeding the abandoned background streams
        for t in bg_threads:
            t.join(timeout=10)
        out["stream_first_byte_ms_p50"] = round(statistics.median(first_ms), 4)

        # syscalls per delivered token over one long stream, counted on
        # the probe channel's OWN server-side connection (the background
        # channels above left others in srv._conns)
        local = ch.sock.getsockname()
        conn = next(c for c in srv._conns
                    if tuple(c.addr) == tuple(local))
        s0 = _io_stats(conn.io)
        c0 = _io_stats(ch.io)
        got = list(ch.server_stream("/bench.Transport/Tokens",
                                    {"n": stream_tokens}))
        assert [m["t"] for m in got] == list(range(stream_tokens)), \
            "stream dropped or reordered tokens"
        s1 = _io_stats(conn.io)
        c1 = _io_stats(ch.io)
        srv_sys, srv_frames = s1[0] - s0[0], s1[1] - s0[1]
        cli_sys = c1[0] - c0[0]
        out["syscalls_per_token"] = round((srv_sys + cli_sys)
                                          / stream_tokens, 3)
        out["server_syscalls_per_token"] = round(srv_sys / stream_tokens, 3)
        out["client_syscalls_per_token"] = round(cli_sys / stream_tokens, 3)
        out["frames_per_syscall"] = round(srv_frames / max(1, srv_sys), 3)
        out["headers_with_first_data"] = conn.io.coalesced_header_data > 0

        spans: dict[str, list[float]] = {}
        for sp in shim.exporter.spans:
            if sp.name.startswith("grpc."):
                spans.setdefault(sp.name, []).append(sp.duration_us / 1e3)
        out["stage_p50_ms"] = {
            k: round(statistics.median(v), 4) for k, v in sorted(spans.items())}
    finally:
        ch.close()
        srv.stop()
        producer.stop()
    return out


def bench_hpack(fast: bool, iters: int) -> float:
    """ns per response header+trailer encode under connection churn —
    the first-response cost every NEW connection pays. The before arm
    is the legacy stateful path (fresh per-connection Encoder walks the
    Huffman bit-packer for every string); the after arm is the server's
    actual fast path: pre-encoded stateless blocks whose per-(name,
    value) fragments live in a module-level cache that survives
    connection churn."""
    resp = [(":status", "200"), ("content-type", "application/grpc")]
    trailer = [("grpc-status", "0")]
    t0 = time.perf_counter()
    for _ in range(iters):
        if fast:
            hpack.encode_stateless(resp)
            hpack.encode_stateless(trailer)
        else:
            enc = hpack.Encoder(memo=False)  # fresh table: a new conn
            enc.encode(resp)
            enc.encode(trailer)
    return (time.perf_counter() - t0) / iters * 1e9


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="reduced CI run; exits non-zero on invariant breaks")
    ap.add_argument("--out", default="TRANSPORT_BENCH.json",
                    help="artifact path (full runs only)")
    args = ap.parse_args(argv)

    if args.smoke:
        unary_n, stream_iters, stream_tokens, hpack_iters = 50, 40, 128, 2000
    else:
        unary_n, stream_iters, stream_tokens, hpack_iters = 400, 300, 512, 20000

    log("transport_bench: BEFORE arm (TransportOptions.legacy)")
    before = run_arm("before", TransportOptions.legacy(), unary_n=unary_n,
                     stream_iters=stream_iters, stream_tokens=stream_tokens,
                     gap_s=0.0005)
    print(json.dumps({"partial": "after arm pending", "before": before}),
          flush=True)
    log("transport_bench: AFTER arm (fast path)")
    after = run_arm("after", TransportOptions(), unary_n=unary_n,
                    stream_iters=stream_iters, stream_tokens=stream_tokens,
                    gap_s=0.0005)

    before["hpack_encode_ns"] = round(bench_hpack(False, hpack_iters), 1)
    after["hpack_encode_ns"] = round(bench_hpack(True, hpack_iters), 1)

    fb_b = before["stream_first_byte_ms_p50"]
    fb_a = after["stream_first_byte_ms_p50"]
    sc_b = before["syscalls_per_token"]
    sc_a = after["syscalls_per_token"]
    artifact = {
        "bench": "transport-loopback",
        "captured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "mode": "cpu-loopback",
        "smoke": bool(args.smoke),
        "before": before,
        "after": after,
        "improvement": {
            "first_byte_reduction_pct": round(100 * (1 - fb_a / fb_b), 1),
            "syscalls_per_token_ratio": round(sc_b / max(sc_a, 1e-9), 2),
            "hpack_encode_speedup": round(
                before["hpack_encode_ns"] / max(after["hpack_encode_ns"], 1e-9),
                2),
        },
    }

    failures = []
    if not after["headers_with_first_data"]:
        failures.append("fast arm did not coalesce HEADERS with first DATA")
    if sc_a >= sc_b:
        failures.append(
            f"no syscall win: before={sc_b}/token after={sc_a}/token")
    if not args.smoke:
        # acceptance thresholds only on full runs — smoke boxes are noisy
        red = artifact["improvement"]["first_byte_reduction_pct"]
        if red < 40:
            failures.append(f"first-byte reduction {red}% < 40%")
        if artifact["improvement"]["syscalls_per_token_ratio"] < 2:
            failures.append(
                f"syscall ratio {artifact['improvement']['syscalls_per_token_ratio']}x < 2x")
    if failures:
        artifact["failures"] = failures

    if not args.smoke:
        Path(args.out).write_text(json.dumps(artifact, indent=2) + "\n")
        log(f"artifact written to {args.out}")
    print(json.dumps(artifact), flush=True)
    if failures:
        log("FAIL: " + "; ".join(failures))
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
