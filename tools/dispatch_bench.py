"""Serial-vs-pipelined decode dispatch A/B (CPU; no chip lock).

The 2026-07-31 device capture (BENCH_CANDIDATE.json) put the fused
decode step at 35.43 ms but the end-to-end dispatched step at 46.15 ms:
~23% of every decode block was host overhead — reap ``device_get``,
Python token delivery, re-dispatch with a ~1.9 ms floor — during which
the device sat idle. The depth-2 dispatch pipeline
(``TPU_DECODE_PIPELINE``, docs/advanced-guide/serving-scheduler.md)
closes that gap by keeping a second fused block queued on the device
stream while the host reaps the first.

This harness proves the mechanism on the CPU backend, where the same
loop runs with the same instrumentation:

  arm "serial"     — GenerationEngine(decode_pipeline=1): the old
                     dispatch -> overlap-admissions -> reap loop.
  arm "pipelined"  — decode_pipeline=2: block N+1 dispatched before
                     block N is reaped.

Phase 1 (steady decode): identical seeded greedy workloads through both
arms. Gates: token-exact across arms (and vs the cache-free oracle),
inter-block host-gap p50 reduced >= 50%, the pipelined arm keeps >= 1
block queued at a majority of steady-state reaps, and admits >= served.

Phase 2 (mixed load): background throughput-class decodes + latency-
class TTFT probes on each arm. Gate: the pipelined arm's latency TTFT
p50 stays within the noise bound of the serial arm's (the depth policy
drops to 1 while a latency admission waits, so pipelining must not buy
throughput with TTFT).

Conventions (tools/README.md): the LAST stdout line is the JSON
artifact; ``--smoke`` is the CI gate (small shapes, same invariants);
full runs write ``DECODE_BENCH.json`` next to the repo root. Exit is
non-zero only when an invariant fails. The measured ratio re-runs on
device hardware ride along in the artifact's ``platform`` field.
"""

from __future__ import annotations

import json
import statistics
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def log(*a) -> None:
    print(*a, file=sys.stderr, flush=True)


def _build_engine(params, cfg, depth: int, *, slots: int, max_seq: int,
                  buckets, decode_block: int):
    from gofr_tpu.tpu import GenerationEngine

    return GenerationEngine(cfg, params, slots=slots, max_seq=max_seq,
                            prompt_buckets=buckets,
                            decode_block=decode_block,
                            decode_pipeline=depth)


def _reference_greedy(params, cfg, prompt, n):
    import jax.numpy as jnp

    from gofr_tpu.models import llama

    toks = list(prompt)
    for _ in range(n):
        logits = llama.forward(params, cfg, jnp.asarray([toks], jnp.int32))
        toks.append(int(jnp.argmax(logits[0, -1])))
    return toks[len(prompt):]


def run(smoke: bool) -> dict:
    import jax
    import numpy as np

    jax.config.update("jax_platforms", "cpu")

    from gofr_tpu.models import llama
    from gofr_tpu.models.common import LLAMA_CONFIGS
    from gofr_tpu.resilience import SLO_THROUGHPUT

    cfg = LLAMA_CONFIGS["tiny"]
    params = llama.init(cfg, jax.random.PRNGKey(0))
    slots = 4 if smoke else 8
    max_new = 48 if smoke else 160
    probes = 6 if smoke else 15
    buckets, max_seq, K = (8, 16), 512, 4

    rng = np.random.default_rng(17)
    prompts = [rng.integers(1, cfg.vocab_size, int(n)).tolist()
               for n in rng.integers(4, 16, slots)]
    oracle = [_reference_greedy(params, cfg, p, min(8, max_new))
              for p in prompts]

    arms: dict[str, dict] = {}
    tokens_by_arm: dict[str, list[list[int]]] = {}
    failures: list[str] = []

    for name, depth in (("serial", 1), ("pipelined", 2)):
        eng = _build_engine(params, cfg, depth, slots=slots,
                            max_seq=max_seq, buckets=buckets,
                            decode_block=K)
        try:
            eng.warmup()
            # -- phase 1: steady decode -------------------------------------
            t0 = time.perf_counter()
            streams = [eng.generate(p, max_new_tokens=max_new)
                       for p in prompts]
            outs = [s.tokens() for s in streams]
            dt = time.perf_counter() - t0
            tokens_by_arm[name] = outs
            total = sum(len(o) for o in outs)
            pipe = eng.stats()["scheduler"]["pipeline"]
            served = sum(1 for o in outs if o)
            admits = eng.stats()["total_requests"]
            arm = {
                "depth": depth,
                "tok_s": round(total / dt, 1),
                "tokens": total,
                "gap_p50_ms": pipe["gap_p50_ms"],
                "gap_samples": pipe["gap_samples"],
                "reaps": pipe["reaps"],
                "overlapped_reaps": pipe["overlapped_reaps"],
                "admits": admits,
                "served": served,
            }
            if admits < served:
                failures.append(f"{name}: admits {admits} < served {served}")
            for o, want in zip(outs, oracle):
                if o[:len(want)] != want:
                    failures.append(f"{name}: diverged from greedy oracle")
                    break

            # -- phase 2: latency TTFT under mixed load ---------------------
            bg = [eng.generate(rng.integers(1, cfg.vocab_size, 8).tolist(),
                               max_new_tokens=100_000,
                               slo_class=SLO_THROUGHPUT)
                  for _ in range(max(1, slots - 2))]
            time.sleep(0.2)  # reach steady background decode
            samples = []
            for _ in range(probes):
                prompt = rng.integers(1, cfg.vocab_size, 8).tolist()
                time.sleep(float(rng.uniform(0.0, 0.05)))
                t0 = time.perf_counter()
                s = eng.generate(prompt, max_new_tokens=2)
                next(iter(s))
                samples.append((time.perf_counter() - t0) * 1e3)
                s.cancel()
                list(s)
            for b in bg:
                b.cancel()
                list(b)
            arm["ttft_lat_p50_ms"] = round(statistics.median(samples), 2)
            arms[name] = arm
            log(f"  {name}: {arm['tok_s']} tok/s, gap p50 "
                f"{arm['gap_p50_ms']} ms, {arm['overlapped_reaps']}/"
                f"{arm['reaps']} overlapped reaps, latency TTFT p50 "
                f"{arm['ttft_lat_p50_ms']} ms")
        finally:
            eng.close()

    # -- invariants --------------------------------------------------------
    if tokens_by_arm["serial"] != tokens_by_arm["pipelined"]:
        failures.append("depth-2 tokens differ from depth-1")
    g_serial = arms["serial"]["gap_p50_ms"]
    g_piped = arms["pipelined"]["gap_p50_ms"]
    reduction = 0.0
    if g_serial is None or g_piped is None:
        failures.append("missing gap samples")
    else:
        reduction = 100.0 * (1 - g_piped / g_serial) if g_serial else 0.0
        if g_piped > 0.5 * g_serial:
            failures.append(f"gap p50 reduced only {reduction:.0f}% "
                            f"({g_serial} -> {g_piped} ms; need >= 50%)")
    reaps = arms["pipelined"]["reaps"]
    overlapped = arms["pipelined"]["overlapped_reaps"]
    if reaps == 0 or overlapped * 2 < reaps:
        failures.append(f"pipelined arm kept a block queued at only "
                        f"{overlapped}/{reaps} reaps (need a majority)")
    ttft_ratio = (arms["pipelined"]["ttft_lat_p50_ms"]
                  / max(arms["serial"]["ttft_lat_p50_ms"], 1e-9))
    # CPU noise floor: the depth policy pins latency admissions to one
    # in-flight block, so the p50 must stay within 3x of serial (device
    # re-runs gate tighter against SLO_BENCH)
    if ttft_ratio > 3.0:
        failures.append(f"latency TTFT p50 ratio {ttft_ratio:.2f} > 3.0")

    out = {
        "bench": "dispatch_pipeline",
        "smoke": smoke,
        "platform": "cpu",
        "captured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "arms": arms,
        "exact_tokens": tokens_by_arm["serial"] == tokens_by_arm["pipelined"],
        "gap_p50_ms": {"serial": g_serial, "pipelined": g_piped},
        "gap_reduction_pct": round(reduction, 1),
        "overlapped_frac": round(overlapped / reaps, 3) if reaps else 0.0,
        "ttft_ratio_pipelined_vs_serial": round(ttft_ratio, 3),
        "ok": not failures,
    }
    if failures:
        out["failures"] = failures
    return out


def main() -> int:
    smoke = "--smoke" in sys.argv[1:]
    result = run(smoke)
    if not smoke and result["ok"]:
        import os

        path = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "DECODE_BENCH.json")
        with open(path, "w") as f:
            json.dump(result, f, indent=1, sort_keys=True)
            f.write("\n")
        log(f"  wrote {path}")
    print(json.dumps(result), flush=True)
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
