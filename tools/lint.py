#!/usr/bin/env python
"""Hermetic lint gate (stdlib-only) for gofr_tpu — style-pass shim.

Reference parity: the reference CI blocks on golangci-lint
(.github/workflows/go.yml:231-239 in the reference repo). This repo's
CI lint job prefers `ruff check .` (config in pyproject.toml); this
tool is the zero-dependency fallback that runs in hermetic
environments where ruff cannot be installed.

The rule implementations live in tools/gofrlint/ (the multi-pass
analyzer: style + lock discipline + TPU hot-path); this entry point
runs JUST the style pass with the same `# noqa` semantics:

  F401  unused import (module scope; __init__.py re-exports exempt)
  F811  redefinition of a top-level def/class by another def/class
  E501  line longer than MAX_LINE columns
  E711  comparison to None with ==/!=
  E722  bare `except:`
  B006  mutable default argument (list/dict/set literal or call)
  B011  assert on a non-empty tuple literal (always true)
  F601  duplicate literal key in a dict display
  F541  f-string without any placeholder
  W291  trailing whitespace / W191 tab indentation
  T201  bare `print(` inside gofr_tpu/ — framework output must go
        through glog so every line carries trace correlation
  E999  syntax error

EVERY rule honors `# noqa` (suppress the line) and `# noqa: CODE[,..]`
(suppress the listed codes) — suppression is applied centrally in
gofrlint, not per rule. For the full analyzer (lock discipline GL001/
GL002, TPU hot-path GL101-GL103, resource lifetime GL201-GL204,
distributed safety GL301-GL304, baseline workflow) run
`python -m tools.gofrlint` — see docs/advanced-guide/static-analysis.md.

Usage: python tools/lint.py [paths...]   (default: the repo)
Exit code 1 when any finding is reported.
"""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from tools.gofrlint import style                      # noqa: E402
from tools.gofrlint.base import (                     # noqa: E402
    MAX_LINE, SKIP_DIRS, Finding, SourceFile, collect_files)

# Stable API for tests and embedders: the Checker class (AST rules,
# constructor signature pinned by tests/test_lint_tool.py) is the
# gofrlint style checker.
Checker = style.Checker

__all__ = ["Checker", "Finding", "MAX_LINE", "SKIP_DIRS", "lint_file",
           "main"]


def lint_file(path: Path) -> list[Finding]:
    sf = SourceFile(path, str(path))
    return [f for f in style.run(sf) if not sf.suppressed(f)]


def main(argv: list[str]) -> int:
    roots = [Path(a) for a in argv] or [Path(__file__).resolve().parent.parent]
    files = collect_files(roots)
    findings: list[Finding] = []
    for f in files:
        findings.extend(lint_file(f))
    findings.sort(key=lambda fi: (fi.path, fi.line, fi.code))
    for fi in findings:
        print(fi)
    print(f"{len(findings)} finding(s) in {len(files)} file(s)",
          file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
