#!/usr/bin/env python
"""TTFT hit-vs-miss benchmark for the hierarchical prefix KV cache.

CPU-only (JAX_PLATFORMS=cpu, no chip lock): the point is the RATIO
between a cold prefill and a tier restore on identical hardware, and
the per-tier plumbing invariants — not absolute chip numbers. One
process hosts two engines sharing one in-process RESP fake:

  engine A  T0 (2 pool rows) + T1 (host DRAM) + T2 (Redis write-through)
  engine B  a "replica": T0 only + the same Redis — its first sight of
            the shared prefix must restore from T2

Scenario: a 512-token shared prefix (the shared-system-prompt shape)
with per-request tails. Arms, all timed as client-observed TTFT
(generate() -> first token):

  cold     unrelated random prompts — full chunked prefill
  t0_hit   shared prefix resident in an HBM pool row — one row copy
  t1_hit   prefix evicted to host DRAM first — device_put + promote
  t2_hit   replica engine, prefix only in Redis — fetch + promote

Invariants checked every run (smoke included): every hit stream yields
the EXACT tokens of a cache-free reference engine (int8 cache: tier
round trips are lossless), and T1 and T2 must each actually serve hits.
Full runs additionally gate: t0 hit TTFT >= 40% below cold.

Output follows the bench stdout contract (tools/README.md): the LAST
stdout line is the JSON artifact; earlier stdout lines are partial
snapshots; progress goes to stderr. Full runs write KVCACHE_BENCH.json.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import statistics
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from gofr_tpu.datasource.redisclient import RedisClient  # noqa: E402
from gofr_tpu.models import LLAMA_CONFIGS, llama  # noqa: E402
from gofr_tpu.testutil.redisfake import FakeRedisServer  # noqa: E402
from gofr_tpu.tpu import GenerationEngine  # noqa: E402
from gofr_tpu.tpu.kvcache import KVCacheOptions  # noqa: E402


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def ttft_ms(eng, prompt, max_new=4):
    """Client-observed TTFT: generate() call to first delivered token.
    Drains the stream so the slot retires before the next probe."""
    t0 = time.perf_counter()
    stream = eng.generate(prompt, max_new_tokens=max_new)
    it = iter(stream)
    first = next(it)
    ms = (time.perf_counter() - t0) * 1e3
    toks = [first] + list(it)
    return ms, toks


class Harness:
    def __init__(self, prefix_tokens: int, reps: int):
        self.reps = reps
        if prefix_tokens >= 512:
            self.cfg = dataclasses.replace(LLAMA_CONFIGS["tiny"],
                                           max_seq=1024)
            self.buckets = (32, 64, 128, 256, 512)
            max_seq, store_min, self.block = 1024, 256, 32
        else:  # smoke geometry
            self.cfg = LLAMA_CONFIGS["tiny"]
            self.buckets = (8, 16, 32)
            max_seq, store_min, self.block = 128, 16, 8
        self.params = llama.init(self.cfg, jax.random.PRNGKey(1))
        self.rng = np.random.default_rng(42)
        self.prefix = self.rng.integers(
            1, self.cfg.vocab_size, prefix_tokens).tolist()
        self.tail_n = self.buckets[0] // 2
        self.srv = FakeRedisServer()

        def eng(**kw):
            return GenerationEngine(
                self.cfg, self.params, slots=2, max_seq=max_seq,
                prompt_buckets=self.buckets, kv_dtype=jnp.int8,
                prefix_store_min=store_min, **kw)

        log("kvcache_bench: building engines (A=3 tiers, B=replica, "
            "M=no cache)")
        self.a = eng(prefix_cache_slots=2, kvcache=KVCacheOptions(
            block=self.block, host_mb=256, epoch_refresh_s=0.0,
            redis=RedisClient(self.srv.host, self.srv.port)))
        self.b = eng(prefix_cache_slots=2, kvcache=KVCacheOptions(
            block=self.block, host_mb=0, epoch_refresh_s=0.0,
            redis=RedisClient(self.srv.host, self.srv.port)))
        self.miss = eng()

    def close(self):
        self.a.close()
        self.b.close()
        self.miss.close()
        self.srv.close()

    def tail(self):
        return self.rng.integers(1, self.cfg.vocab_size,
                                 self.tail_n).tolist()

    def rand_prompt(self):
        return self.rng.integers(1, self.cfg.vocab_size,
                                 len(self.prefix)).tolist()

    def evict_t0(self, eng):
        """Push two unrelated stored prompts through — with 2 pool
        rows, anything previously resident leaves T0."""
        for _ in range(2):
            eng.generate(self.rand_prompt(), max_new_tokens=1).tokens()

    def warm(self):
        """Compile every program each arm will hit, OFF the clock:
        bucket prefills + chunk lattice (warmup()), then one store /
        T0-hit / T1-promote / T2-fetch cycle with a throwaway prefix."""
        log("kvcache_bench: warmup (compiles)")
        for e in (self.a, self.b, self.miss):
            e.warmup()
        warm_prefix = self.rng.integers(
            1, self.cfg.vocab_size, len(self.prefix)).tolist()
        self.a.generate(warm_prefix + self.tail(), max_new_tokens=1).tokens()
        self.a.generate(warm_prefix + self.tail(), max_new_tokens=1).tokens()
        self.evict_t0(self.a)   # spill -> T1
        self.a.generate(warm_prefix + self.tail(), max_new_tokens=1).tokens()
        self.b.generate(warm_prefix + self.tail(), max_new_tokens=1).tokens()
        self.evict_t0(self.b)
        self.miss.generate(warm_prefix + self.tail(),
                           max_new_tokens=1).tokens()

    # -- arms ---------------------------------------------------------------
    def arm_cold(self):
        out = []
        for _ in range(self.reps):
            ms, _ = ttft_ms(self.a, self.rand_prompt() + self.tail())
            out.append(ms)
        return out

    def arm_t0(self, probe_tail, want):
        # plant the shared prefix, then time repeat hits
        self.a.generate(self.prefix + self.tail(), max_new_tokens=1).tokens()
        out, exact = [], True
        for i in range(self.reps):
            tail = probe_tail if i == 0 else self.tail()
            ms, toks = ttft_ms(self.a, self.prefix + tail)
            out.append(ms)
            if i == 0:
                exact = toks == want
        return out, exact

    def arm_t1(self, probe_tail, want):
        out, exact = [], True
        for i in range(self.reps):
            self.evict_t0(self.a)  # spill the prefix entries to host
            tail = probe_tail if i == 0 else self.tail()
            ms, toks = ttft_ms(self.a, self.prefix + tail)
            out.append(ms)
            if i == 0:
                exact = toks == want
        return out, exact

    def arm_t2(self, probe_tail, want):
        out, exact = [], True
        for i in range(self.reps):
            self.evict_t0(self.b)  # host tier off: only Redis has it
            tail = probe_tail if i == 0 else self.tail()
            ms, toks = ttft_ms(self.b, self.prefix + tail)
            out.append(ms)
            if i == 0:
                exact = toks == want
        return out, exact


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="reduced CI run; exits non-zero on invariant "
                         "breaks (no artifact file)")
    ap.add_argument("--out", default="KVCACHE_BENCH.json",
                    help="artifact path (full runs only)")
    ap.add_argument("--prefix-tokens", type=int, default=None)
    ap.add_argument("--reps", type=int, default=None)
    args = ap.parse_args(argv)

    prefix_tokens = args.prefix_tokens or (64 if args.smoke else 512)
    reps = args.reps or (2 if args.smoke else 5)

    h = Harness(prefix_tokens, reps)
    artifact = {
        "bench": "kvcache-tiered-ttft",
        "captured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "mode": "cpu",
        "smoke": bool(args.smoke),
        "scenario": {
            "model": f"tiny(max_seq={h.cfg.max_seq})",
            "kv_dtype": "int8",
            "prefix_tokens": prefix_tokens,
            "tail_tokens": h.tail_n,
            "block": h.block,
            "pool_rows": 2,
            "reps": reps,
        },
    }
    try:
        h.warm()
        probe_tail = h.tail()
        _, want = ttft_ms(h.miss, h.prefix + probe_tail)  # reference

        log("kvcache_bench: cold arm")
        cold = h.arm_cold()
        print(json.dumps({"partial": "hit arms pending",
                          "cold_ms": cold}), flush=True)
        log("kvcache_bench: t0 arm")
        t0, exact0 = h.arm_t0(probe_tail, want)
        log("kvcache_bench: t1 arm")
        t1, exact1 = h.arm_t1(probe_tail, want)
        log("kvcache_bench: t2 arm (replica via Redis)")
        t2, exact2 = h.arm_t2(probe_tail, want)

        st_a = h.a.stats()["prefix_cache"]["tiers"]
        st_b = h.b.stats()["prefix_cache"]["tiers"]
        med = statistics.median
        cold_p50 = med(cold)
        artifact["ttft_ms"] = {
            "cold_p50": round(cold_p50, 3),
            "t0_hit_p50": round(med(t0), 3),
            "t1_hit_p50": round(med(t1), 3),
            "t2_hit_p50": round(med(t2), 3),
        }
        artifact["improvement_pct"] = {
            t: round(100 * (1 - artifact["ttft_ms"][f"{t}_hit_p50"]
                            / cold_p50), 1)
            for t in ("t0", "t1", "t2")}
        artifact["tier_hits"] = {
            "t0": st_a["t0"]["hits"],
            "t1": st_a["t1"]["hits"],
            "t2": st_b["t2"]["hits"],
        }
        artifact["exact_tokens"] = bool(exact0 and exact1 and exact2)
        artifact["redis"] = {k: st_a["t2"][k] for k in
                             ("blocks_put", "bytes_put", "errors")}

        failures = []
        if not artifact["exact_tokens"]:
            failures.append("hit streams diverged from the cache-free "
                            "reference")
        if artifact["tier_hits"]["t1"] < 1:
            failures.append("T1 served no hits in the scenario")
        if artifact["tier_hits"]["t2"] < 1:
            failures.append("T2 served no hits in the scenario")
        if artifact["redis"]["errors"]:
            failures.append(f"redis tier errors: {artifact['redis']}")
        if not args.smoke:
            # acceptance thresholds only on full runs — smoke geometry
            # (64-token prefix) is not the 512-token claim
            if artifact["improvement_pct"]["t0"] < 40:
                failures.append(
                    f"t0 hit TTFT only {artifact['improvement_pct']['t0']}% "
                    "below cold (< 40%)")
    except Exception as e:  # noqa: BLE001 — artifact over traceback
        failures = [f"harness error: {e!r}"]
        artifact["error"] = repr(e)
    finally:
        h.close()

    if failures:
        artifact["failures"] = failures
    if not args.smoke and "error" not in artifact:
        Path(args.out).write_text(json.dumps(artifact, indent=2) + "\n")
        log(f"artifact written to {args.out}")
    print(json.dumps(artifact), flush=True)
    if failures:
        log("FAIL: " + "; ".join(failures))
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
