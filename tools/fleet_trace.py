#!/usr/bin/env python
"""Fetch (or self-host and validate) the FLEET-merged timeline: every
process's chrome trace re-based onto one clock axis, with flow arrows
joining each request's hops across processes.

Two modes:

  --url http://host:2121 [--last-ms N] [--out trace.json]
      Fetch ``/debug/timeline?fleet=1`` from a running app's metrics
      port and write the merged Perfetto JSON (stdout or --out). The
      serving process pulls each peer it knows about (pd handshake,
      gateway health poll, TPU_OBS_PEERS) and merges on ITS clock.

  --smoke / (no args: full run)
      CPU-only, no chip lock: host a real gateway + a real replica App
      (tiny engine behind /generate) on ephemeral ports, drive a traced
      request through the gateway, and validate the merged trace
      against the run's KNOWN shape:

        - >= 2 process track groups (gateway + replica), zero degraded
          peers;
        - the request's trace id has hop slices in BOTH processes,
          joined by flow arrows (``s``/``f`` present);
        - the replica's estimated clock offset is ~0 (same host) and
          within its own reported uncertainty;
        - the replica wide event's critical-path breakdown sums to the
          end-to-end duration within 5%;
        - ``/debug/request?trace_id=...`` assembles the cross-process
          story (gateway + replica events, not partial).

      Full runs add a P/D pair arm (PDPrefill -> KVIngestServer over
      localhost) gating the HELLO/END clock carriers, ``kv_transfer_s``
      in the decode wide event, and the ship-duration/backlog metrics,
      then write FLEET_OBS_BENCH.json.

Output follows the bench stdout contract (tools/README.md): the LAST
stdout line is the JSON artifact; progress goes to stderr; failures
land in a ``failures`` list instead of a non-zero exit.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.request
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

TRACE_ID = "f1ee70b5e12a4b0fa11ce0ffee0bd000"
TRACEPARENT = f"00-{TRACE_ID}-00f067aa0ba902b7-01"


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


# -- fetch mode ---------------------------------------------------------------

def fetch(url: str, last_ms: float | None, out: str | None) -> int:
    target = url.rstrip("/") + "/debug/timeline?fleet=1"
    if last_ms is not None:
        target += f"&last_ms={last_ms}"
    log(f"fetching {target}")
    with urllib.request.urlopen(target, timeout=30) as r:
        payload = r.read()
    merged = json.loads(payload)  # refuse to write a non-JSON body
    fleet = (merged.get("otherData") or {}).get("fleet") or {}
    log(f"merged {len(fleet.get('processes', []))} processes, "
        f"{fleet.get('traces_joined', 0)} traces joined, "
        f"degraded={fleet.get('degraded', [])}")
    if out:
        Path(out).write_bytes(payload)
        log(f"wrote {out} ({len(payload)} bytes) — load in ui.perfetto.dev")
    else:
        sys.stdout.write(payload.decode())
    return 0


# -- self-hosted gateway + replica arm ----------------------------------------

def _get_json(port: int, path: str):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                timeout=10) as r:
        return json.loads(r.read())


def _replica_app(name: str, params):
    """A real App whose /generate drives a real tiny engine wired to
    the App's OWN Observe bundle — so its metrics port serves the
    engine's timeline and wide events, like a production replica."""
    from gofr_tpu import App
    from gofr_tpu.config import MapConfig
    from gofr_tpu.models import LLAMA_CONFIGS
    from gofr_tpu.tpu import GenerationEngine

    app = App(MapConfig({"HTTP_PORT": "0", "METRICS_PORT": "0",
                         "APP_NAME": name, "LOG_LEVEL": "ERROR"}))
    eng = GenerationEngine(LLAMA_CONFIGS["tiny"], params, slots=2,
                           max_seq=256, prompt_buckets=(8, 16, 32),
                           prefill_chunk=16, decode_block=4,
                           metrics=app.container.metrics,
                           observe=app.container.observe)

    @app.post("/generate")
    def generate(ctx):
        body = ctx.bind()
        stream = eng.generate(
            [int(t) for t in body["tokens"]],
            max_new_tokens=int(body.get("max_new_tokens", 8)),
            traceparent=ctx.header("traceparent"))

        def lines():
            for tok in stream:
                yield (json.dumps({"token": int(tok)}) + "\n").encode()

        ctx.stream(lines())
        return None

    app.run(block=False)
    return app, eng


def _gateway_app(replica_address: str):
    from gofr_tpu import App
    from gofr_tpu.config import MapConfig

    gw = App(MapConfig({
        "HTTP_PORT": "0", "METRICS_PORT": "0", "APP_NAME": "gw",
        "LOG_LEVEL": "ERROR", "TPU_SERVING_ROLE": "gateway",
        "TPU_GATEWAY_REPLICAS": replica_address,
        "TPU_GATEWAY_HEALTH_INTERVAL_S": "0.2",
        "TPU_GATEWAY_CONNECT_TIMEOUT_S": "2.0"}))
    gw.run(block=False)
    return gw


def _post_generate(port: int, tokens, max_new: int, headers: dict):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/generate",
        data=json.dumps({"tokens": [int(t) for t in tokens],
                         "max_new_tokens": max_new}).encode(),
        headers={"Content-Type": "application/json", **headers},
        method="POST")
    with urllib.request.urlopen(req, timeout=30) as resp:
        return [json.loads(line) for line in
                resp.read().decode().splitlines() if line]


def _validate_merged(merged: dict, trace_id: str) -> list[str]:
    failures: list[str] = []
    fleet = (merged.get("otherData") or {}).get("fleet") or {}
    procs = fleet.get("processes") or []
    if len(procs) < 2:
        failures.append(f"merged trace has {len(procs)} processes, want >=2")
    if fleet.get("degraded"):
        failures.append(f"degraded peers in a healthy fleet: "
                        f"{fleet['degraded']}")
    if not fleet.get("traces_joined"):
        failures.append("no trace id joined across processes")

    ev = merged.get("traceEvents", [])
    names = sum(1 for e in ev
                if e.get("ph") == "M" and e.get("name") == "process_name")
    if names < 2:
        failures.append(f"{names} process_name metadata entries, want >=2")
    req_pids = {e["pid"] for e in ev
                if e.get("ph") == "X" and e.get("cat") == "request"
                and (e.get("args") or {}).get("trace_id") == trace_id}
    if len(req_pids) < 2:
        failures.append(f"trace {trace_id[:8]} hop slices on pids "
                        f"{sorted(req_pids)}, want both processes")
    flow_phs = {e["ph"] for e in ev if e.get("name") == "request-hop"}
    if not {"s", "f"} <= flow_phs:
        failures.append(f"flow arrows incomplete: phases {sorted(flow_phs)}")
    # replica offset: same host, so ~0 and inside its own error bar
    for p in procs:
        if p.get("pid") == 1:
            continue
        off, unc = p.get("offset_s"), p.get("uncertainty_s")
        if off is None:
            failures.append(f"peer {p.get('name')} merged unaligned")
        elif abs(off) > (unc or 0.0) + 0.05:
            failures.append(
                f"peer {p.get('name')} offset {off * 1e3:.2f}ms outside "
                f"uncertainty {((unc or 0.0)) * 1e3:.2f}ms (+50ms slack)")
    return failures


def _validate_story(story: dict, trace_id: str) -> tuple[list[str], float]:
    """Gates on /debug/request: both processes contribute events, and
    the engine-side breakdown telescopes to the duration within 5%."""
    failures: list[str] = []
    ratio = 0.0
    if story.get("trace_id") != trace_id:
        failures.append("request story echoes the wrong trace id")
    if story.get("partial"):
        failures.append(f"healthy fleet but partial story: "
                        f"{story.get('degraded')}")
    stories = story.get("stories") or []
    with_events = [s for s in stories if s.get("events")]
    if len(with_events) < 2:
        failures.append(f"{len(with_events)} processes hold events for the "
                        "trace, want gateway AND replica")
    for s in stories:
        for ev in s.get("events") or []:
            bd = ev.get("breakdown")
            dur = ev.get("duration_s")
            if s.get("source") != "peer" or not bd or not dur:
                continue  # the 5% gate is on the engine-side event
            ratio = sum(bd.values()) / dur
            if abs(ratio - 1.0) > 0.05:
                failures.append(
                    f"breakdown sums to {ratio:.3f}x the end-to-end "
                    f"duration (segments {bd}, duration {dur:.4f}s)")
    if ratio == 0.0:
        failures.append("no engine wide event carried a breakdown")
    return failures, ratio


def run_gateway_arm(params, n_requests: int) -> tuple[dict, list[str]]:
    arm: dict = {}
    failures: list[str] = []
    log("fleet_trace: starting replica (tiny engine) + gateway")
    rep, eng = _replica_app("replica-a", params)
    gw = _gateway_app(f"127.0.0.1:{rep.http_port}")
    try:
        # deterministic clock samples: each health poll is one NTP
        # exchange (the background poller keeps refreshing after)
        for _ in range(4):
            gw._gateway.table.poll_once()
        import numpy as np

        rng = np.random.default_rng(11)
        V = eng.cfg.vocab_size
        lines = _post_generate(gw.http_port, rng.integers(1, V, 12),
                               6, {"traceparent": TRACEPARENT})
        if len(lines) != 6:
            failures.append(f"traced request returned {len(lines)} tokens, "
                            "want 6")
        for i in range(n_requests - 1):  # background traffic, own traces
            _post_generate(gw.http_port, rng.integers(1, V, 8), 4, {})
        time.sleep(0.3)  # wide events flush off the serving path

        merged = _get_json(gw.metrics_port, "/debug/timeline?fleet=1")
        fleet = (merged.get("otherData") or {}).get("fleet") or {}
        arm["processes"] = len(fleet.get("processes") or [])
        arm["traces_joined"] = fleet.get("traces_joined")
        arm["flow_events"] = fleet.get("flow_events")
        arm["degraded"] = fleet.get("degraded")
        for p in fleet.get("processes") or []:
            if p.get("pid") != 1 and p.get("offset_s") is not None:
                arm["replica_offset_ms"] = round(p["offset_s"] * 1e3, 3)
                arm["replica_uncertainty_ms"] = round(
                    (p.get("uncertainty_s") or 0.0) * 1e3, 3)
        failures += _validate_merged(merged, TRACE_ID)

        story = _get_json(gw.metrics_port,
                          f"/debug/request?trace_id={TRACE_ID}")
        story_failures, ratio = _validate_story(story, TRACE_ID)
        failures += story_failures
        arm["request_events_found"] = story.get("found")
        arm["breakdown_sum_ratio"] = round(ratio, 4)
    finally:
        gw.stop()
        rep.stop()
        eng.close()
    return arm, failures


# -- the P/D pair arm (full runs) ---------------------------------------------

def run_pd_arm(params) -> tuple[dict, list[str]]:
    """PDPrefill -> KVIngestServer over localhost: the HELLO handshake
    and every REQ->END round trip feed the prefill side's clock
    registry; the decode wide event carries ``kv_transfer_s`` beside a
    telescoping breakdown; shipping records duration + backlog."""
    import jax.numpy as jnp

    from gofr_tpu.metrics import Manager, register_framework_metrics
    from gofr_tpu.models import LLAMA_CONFIGS
    from gofr_tpu.observe import Observe
    from gofr_tpu.pd import KVIngestServer, PDPrefill
    from gofr_tpu.tpu import GenerationEngine
    from gofr_tpu.tpu.kvcache import model_fingerprint

    arm: dict = {}
    failures: list[str] = []
    cfg = LLAMA_CONFIGS["tiny"]
    fp = model_fingerprint(cfg, params, extra="pd")

    def engine(observe, metrics):
        return GenerationEngine(cfg, params, slots=2, max_seq=128,
                                prompt_buckets=(16, 32), kv_dtype=jnp.int8,
                                metrics=metrics, observe=observe)

    pre_metrics = Manager()
    register_framework_metrics(pre_metrics)
    dec_metrics = Manager()
    register_framework_metrics(dec_metrics)
    obs_pre, obs_dec = Observe(metrics=pre_metrics), Observe(
        metrics=dec_metrics)
    log("fleet_trace: starting P/D pair (prefill -> decode over localhost)")
    pre = engine(obs_pre, pre_metrics)
    dec = engine(obs_dec, dec_metrics)
    srv = KVIngestServer(dec, fp, "127.0.0.1", 0, metrics=dec_metrics)
    pd = PDPrefill(pre, fp, "127.0.0.1", srv.port, ship_block=16,
                   metrics=pre_metrics)
    try:
        import numpy as np

        rng = np.random.default_rng(5)
        for _ in range(3):
            toks = pd.generate(rng.integers(1, cfg.vocab_size, 40).tolist(),
                               max_new_tokens=8).tokens()
            if len(toks) != 8:
                failures.append(f"pd relay served {len(toks)} tokens, want 8")
        time.sleep(0.2)

        peers = obs_pre.clock.stats()
        pd_peers = {k: v for k, v in peers.items() if k.startswith("pd:")}
        arm["clock_peers"] = list(pd_peers)
        if not pd_peers:
            failures.append("no decode peer in the prefill clock registry")
        for name, st in pd_peers.items():
            arm["peer_samples"] = st.get("samples")
            arm["peer_offset_ms"] = (round(st["offset_s"] * 1e3, 3)
                                     if st.get("offset_s") is not None
                                     else None)
            arm["peer_uncertainty_ms"] = (
                round(st["uncertainty_s"] * 1e3, 3)
                if st.get("uncertainty_s") is not None else None)
            # HELLO gives 1; each of the 3 ENDs adds one more
            if (st.get("samples") or 0) < 2:
                failures.append(f"{name}: {st.get('samples')} clock samples, "
                                "want HELLO + END carriers")
            if st.get("offset_s") is None:
                failures.append(f"{name}: no usable clock sample")
            elif abs(st["offset_s"]) > (st.get("uncertainty_s") or 0) + 0.05:
                failures.append(
                    f"{name}: offset {st['offset_s'] * 1e3:.2f}ms outside "
                    f"uncertainty (+50ms slack)")

        wide = [e for e in obs_dec.recorder.events(event="request")
                if e.get("kv_transfer_s") is not None]
        arm["decode_wide_with_kv_transfer"] = len(wide)
        if not wide:
            failures.append("no decode wide event carried kv_transfer_s")
        else:
            ev = wide[-1]
            bd, dur = ev.get("breakdown") or {}, ev.get("duration_s")
            if bd and dur:
                ratio = sum(bd.values()) / dur
                arm["decode_breakdown_sum_ratio"] = round(ratio, 4)
                if abs(ratio - 1.0) > 0.05:
                    failures.append(f"decode breakdown sums to {ratio:.3f}x "
                                    f"duration ({bd})")
            else:
                failures.append("decode wide event missing breakdown")

        text = pre_metrics.render_prometheus()
        if "app_tpu_pd_ship_duration" not in text:
            failures.append("no app_tpu_pd_ship_duration samples on the "
                            "prefill side")
        if "app_tpu_wire_backlog_bytes" not in text:
            failures.append("no app_tpu_wire_backlog_bytes gauge on the "
                            "prefill side")
    finally:
        pd.close()
        srv.close()
        pre.close()
        dec.close()
    return arm, failures


def run_bench(smoke: bool) -> dict:
    import jax

    from gofr_tpu.models import LLAMA_CONFIGS, llama

    art: dict = {"bench": "fleet_obs", "smoke": smoke}
    failures: list[str] = []
    params = llama.init(LLAMA_CONFIGS["tiny"], jax.random.PRNGKey(0))

    arm, f = run_gateway_arm(params, n_requests=2 if smoke else 6)
    art["gateway_arm"] = arm
    failures += f

    if not smoke:
        arm, f = run_pd_arm(params)
        art["pd_arm"] = arm
        failures += f

    art["failures"] = failures
    art["ok"] = not failures
    return art


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--url", help="metrics-port base URL of a running app")
    ap.add_argument("--last-ms", type=float, default=None)
    ap.add_argument("--out", help="write the trace/artifact to this file")
    ap.add_argument("--smoke", action="store_true",
                    help="fast CI arm of the self-hosted bench")
    args = ap.parse_args()

    if args.url:
        return fetch(args.url, args.last_ms, args.out)

    art = run_bench(smoke=args.smoke)
    if not args.smoke:
        out = args.out or str(Path(__file__).resolve().parent.parent
                              / "FLEET_OBS_BENCH.json")
        Path(out).write_text(json.dumps(art, indent=2) + "\n")
        log(f"wrote {out}")
    print(json.dumps(art))
    return 0


if __name__ == "__main__":
    sys.exit(main())
