"""TPU hot-path pass: GL101 (host syncs in loops), GL102 (jit recompile
hazards), GL103 (tracer leakage).

GL101 — scope ``gofr_tpu/tpu/``. One host synchronization inside a
decode/step/dispatch loop turns an async dispatch pipeline into a
lockstep crawl; the serving-loop contract is ONE transfer per dispatched
block (tools/README.md timing conventions). Flagged inside any
``for``/``while``/comprehension body:

  - ``jax.device_get(...)`` / ``<x>.device_get(...)``
  - ``jax.block_until_ready(...)``
  - ``<x>.item()``
  - ``np.asarray/np.array/float/int`` over an expression that touches a
    DEVICE-resident attribute (attrs assigned from ``*_jit`` calls,
    ``jax.device_put``, ``jnp.*`` constructors, ``PRNGKey``) or the
    direct result of a ``*_jit`` call.

Cold paths are exempt: functions named warmup/close/drain/stats/
health_check (+ ``_warm*``/``load_*``), ``__init__``, and everything
inside ``except`` handlers (recovery is allowed to block).

GL102 — scope ``gofr_tpu/``. Two recompile/trace hazards around
``jax.jit``: (a) a Python ``if``/``while`` on a traced parameter inside
a jitted function (TracerBoolConversionError at best, silent per-value
recompiles via static fallbacks at worst) — parameters bound static via
``static_argnums/static_argnames`` or ``functools.partial`` are
excluded, as are shape/dtype/ndim/len() tests (static under trace) and
``is None`` pytree-structure checks; (b) a list/dict/set literal passed
at a static position of a known-jitted callable — unhashable statics
raise on every call.

GL103 — scope ``gofr_tpu/``. Writes that escape a traced function:
assigning a module global (or mutating a module-level container, or
setting ``self.X``) inside a jitted function stores a tracer that
outlives the trace.
"""

from __future__ import annotations

import ast

from .base import Finding, SourceFile, _self_attr, in_framework, \
    project_parts

_COLD_NAMES = {"warmup", "close", "drain", "stats", "health_check",
               "__init__", "__del__", "__repr__"}
# matched against the name AFTER leading underscores are stripped, so
# `_warm_pool` and `warm_cache` are both cold
_COLD_PREFIXES = ("warm", "load_")
_DEVICE_CTORS = {"device_put", "PRNGKey", "block_until_ready"}
_JNP_CTORS = {"asarray", "array", "zeros", "ones", "full", "arange"}
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size"}
_MUTATORS = {"append", "extend", "insert", "update", "add", "setdefault"}


def _callee_last(node: ast.expr) -> str | None:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _callee_root(node: ast.expr) -> str | None:
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _is_device_producer(call: ast.expr) -> bool:
    if not isinstance(call, ast.Call):
        return False
    last = _callee_last(call.func)
    if last is None:
        return False
    if "jit" in last:
        return True
    if last in _DEVICE_CTORS:
        return True
    root = _callee_root(call.func)
    return root in ("jnp", "jax") and last in _JNP_CTORS


def _device_attrs(cls: ast.ClassDef) -> set[str]:
    """Attributes that hold device arrays: targets of assignments whose
    RHS is a jit dispatch / device_put / jnp constructor / PRNGKey."""
    out: set[str] = set()
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign):
            continue
        if not _is_device_producer(node.value):
            continue
        stack = list(node.targets)
        while stack:
            t = stack.pop()
            if isinstance(t, (ast.Tuple, ast.List)):
                stack.extend(t.elts)
                continue
            if isinstance(t, ast.Subscript):
                t = t.value
            a = _self_attr(t)
            if a is not None:
                out.add(a)
    return out


class _JitInfo:
    """One jit-traced function: its def node + static parameter names."""

    def __init__(self, fn: ast.AST, static_names: set[str],
                 static_nums: set[int]):
        self.fn = fn
        self.static_names = static_names
        self.static_nums = static_nums


def _jit_call_statics(call: ast.Call) -> tuple[set[str], set[int]]:
    names: set[str] = set()
    nums: set[int] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnames" and isinstance(
                kw.value, (ast.Tuple, ast.List, ast.Constant)):
            elts = kw.value.elts if not isinstance(kw.value, ast.Constant) \
                else [kw.value]
            for e in elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    names.add(e.value)
        if kw.arg == "static_argnums":
            elts = [kw.value]
            if isinstance(kw.value, (ast.Tuple, ast.List)):
                elts = list(kw.value.elts)
            for e in elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, int):
                    nums.add(e.value)
    return names, nums


def _is_jit_name(node: ast.expr) -> bool:
    return _callee_last(node) == "jit"


class HotPathPass:
    def __init__(self):
        self.findings: list[Finding] = []

    def feed(self, sf: SourceFile) -> None:
        if sf.tree is None or not in_framework(sf.path):
            return
        # anchored at the project root like in_framework: an absolute-
        # path check would turn a checkout under /home/tpu/ into
        # all-GL101-everywhere
        in_tpu = "tpu" in project_parts(sf.path)
        defs = self._collect_defs(sf.tree)
        jitted, jit_targets = self._collect_jitted(sf.tree, defs)
        if in_tpu:
            self._gl101(sf, jitted)
        self._gl102_branches(sf, jitted)
        self._gl102_static_args(sf, jit_targets)
        self._gl103(sf, jitted)

    # -- jit discovery -----------------------------------------------------
    def _collect_defs(self, tree: ast.AST) -> dict[str, ast.AST]:
        return {n.name: n for n in ast.walk(tree)
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}

    def _collect_jitted(self, tree: ast.AST, defs: dict[str, ast.AST]
                        ) -> tuple[list[_JitInfo], dict[str, _JitInfo]]:
        """(jit-traced function infos, jitted-callable-name -> info)."""
        jitted: dict[int, _JitInfo] = {}
        targets: dict[str, _JitInfo] = {}
        # partial aliases: name -> (fn name, kw-bound param names)
        partials: dict[str, tuple[str, set[str]]] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and isinstance(node.value,
                                                           ast.Call):
                call = node.value
                if _callee_last(call.func) == "partial" and call.args and \
                        not _is_jit_name(call.args[0]):
                    inner = _callee_last(call.args[0])
                    if inner in defs:
                        bound = {kw.arg for kw in call.keywords if kw.arg}
                        for t in node.targets:
                            nm = _self_attr(t) or (
                                t.id if isinstance(t, ast.Name) else None)
                            if nm:
                                partials[nm] = (inner, bound)
        for node in ast.walk(tree):
            # decorators: @jax.jit / @functools.partial(jax.jit, ...)
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    names: set[str] = set()
                    nums: set[int] = set()
                    hit = False
                    if _is_jit_name(dec):
                        hit = True
                    elif isinstance(dec, ast.Call):
                        if _is_jit_name(dec.func):
                            hit = True
                            names, nums = _jit_call_statics(dec)
                        elif _callee_last(dec.func) == "partial" and \
                                dec.args and _is_jit_name(dec.args[0]):
                            hit = True
                            names, nums = _jit_call_statics(dec)
                    if hit:
                        jitted[id(node)] = _JitInfo(node, names, nums)
            # wrap calls: X = jax.jit(fn, ...)
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call) and \
                    _is_jit_name(node.value.func) and node.value.args:
                names, nums = _jit_call_statics(node.value)
                fn_name = _callee_last(node.value.args[0])
                bound: set[str] = set()
                if fn_name in partials:
                    fn_name, bound = partials[fn_name]
                fn = defs.get(fn_name)
                info = _JitInfo(fn, names | bound, nums)
                if fn is not None:
                    jitted[id(fn)] = info
                for t in node.targets:
                    nm = _self_attr(t) or (
                        t.id if isinstance(t, ast.Name) else None)
                    if nm:
                        targets[nm] = info
        return list(jitted.values()), targets

    # -- GL101 -------------------------------------------------------------
    def _gl101(self, sf: SourceFile, jitted: list[_JitInfo]) -> None:
        jit_ids = {id(j.fn) for j in jitted if j.fn is not None}
        for cls_or_mod in ast.walk(sf.tree):
            if isinstance(cls_or_mod, ast.ClassDef):
                dev = _device_attrs(cls_or_mod)
                for m in cls_or_mod.body:
                    if isinstance(m, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                        self._gl101_fn(sf, m, dev, jit_ids)
        for node in sf.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._gl101_fn(sf, node, set(), jit_ids)

    def _gl101_fn(self, sf: SourceFile, fn: ast.AST, dev: set[str],
                  jit_ids: set[int]) -> None:
        if fn.name in _COLD_NAMES or \
                fn.name.lstrip("_").startswith(_COLD_PREFIXES) or \
                id(fn) in jit_ids:
            return  # cold path, or device-side (traced) code

        def scan(node: ast.AST, in_loop: bool) -> None:
            if isinstance(node, ast.ExceptHandler):
                return  # recovery paths may block
            if isinstance(node, ast.ClassDef):
                return  # methods are scanned by the ClassDef walk in
                        # _gl101 (with the class's device attrs) — a
                        # second pass here would duplicate findings
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if id(node) not in jit_ids:
                    self._gl101_fn(sf, node, dev, jit_ids)
                return
            if in_loop and isinstance(node, ast.Call):
                self._gl101_call(sf, fn, node, dev)
            if isinstance(node, (ast.For, ast.AsyncFor)):
                # the ITERABLE is evaluated once per loop entry — a sync
                # there is the 'fetch the batch once' pattern the rule
                # recommends, not a per-iteration sync
                scan(node.iter, in_loop)
                scan(node.target, True)
                for s in node.body + node.orelse:
                    scan(s, True)
                return
            if isinstance(node, ast.While):
                scan(node.test, True)  # the test DOES run per iteration
                for s in node.body + node.orelse:
                    scan(s, True)
                return
            if isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                                 ast.GeneratorExp)):
                for i, g in enumerate(node.generators):
                    # the first generator's source evaluates once; every
                    # later generator re-iterates per outer element
                    scan(g.iter, True if i else in_loop)
                    scan(g.target, True)
                    for cond in g.ifs:
                        scan(cond, True)
                elts = ([node.key, node.value]
                        if isinstance(node, ast.DictComp) else [node.elt])
                for e in elts:
                    scan(e, True)
                return
            for child in ast.iter_child_nodes(node):
                scan(child, in_loop)

        for child in ast.iter_child_nodes(fn):
            scan(child, False)

    def _gl101_call(self, sf: SourceFile, fn: ast.AST, call: ast.Call,
                    dev: set[str]) -> None:
        last = _callee_last(call.func)
        if last in ("device_get", "block_until_ready"):
            self.findings.append(Finding(
                sf.rel, call.lineno, "GL101",
                f"{last}() inside a loop in {fn.name} — one host sync "
                f"per iteration serializes the device pipeline"))
            return
        if last == "item" and not call.args and \
                isinstance(call.func, ast.Attribute):
            self.findings.append(Finding(
                sf.rel, call.lineno, "GL101",
                f".item() inside a loop in {fn.name} — per-element "
                f"device->host transfer; fetch the batch once"))
            return
        if last in ("asarray", "array", "float", "int") and call.args:
            root = _callee_root(call.func)
            if last in ("float", "int") and root != last:
                return  # someobj.float(...) — not the builtin
            if root == "jnp":
                return  # host->device: async, not a sync
            arg = call.args[0]
            touches_dev = any(
                (a := _self_attr(n)) is not None and a in dev
                for n in ast.walk(arg))
            if touches_dev or _is_device_producer(arg):
                self.findings.append(Finding(
                    sf.rel, call.lineno, "GL101",
                    f"{last}() over device-resident data inside a loop "
                    f"in {fn.name} — implicit device->host sync per "
                    f"iteration"))

    # -- GL102 -------------------------------------------------------------
    def _gl102_branches(self, sf: SourceFile, jitted: list[_JitInfo]
                        ) -> None:
        for info in jitted:
            if info.fn is None:
                continue
            params = [a.arg for a in info.fn.args.posonlyargs
                      + info.fn.args.args + info.fn.args.kwonlyargs]
            traced = {p for i, p in enumerate(params)
                      if p not in ("self", "cls")
                      and p not in info.static_names
                      and i not in info.static_nums}
            for node in ast.walk(info.fn):
                if isinstance(node, (ast.If, ast.While)):
                    name = self._traced_name_in_test(node.test, traced)
                    if name is not None:
                        self.findings.append(Finding(
                            sf.rel, node.lineno, "GL102",
                            f"Python branch on traced parameter "
                            f"{name!r} inside jitted {info.fn.name} — "
                            f"trace error / per-value recompile; use "
                            f"lax.cond/jnp.where or mark it static"))

    def _traced_name_in_test(self, test: ast.expr,
                             traced: set[str]) -> str | None:
        """First traced param referenced by ``test``, after pruning
        trace-static contexts (.shape/.dtype/len()/`is None`)."""
        skip: set[int] = set()
        for n in ast.walk(test):
            if isinstance(n, ast.Attribute) and n.attr in _STATIC_ATTRS:
                for sub in ast.walk(n):
                    skip.add(id(sub))
            if isinstance(n, ast.Call) and \
                    _callee_last(n.func) in ("len", "isinstance",
                                             "getattr", "hasattr"):
                for sub in ast.walk(n):
                    skip.add(id(sub))
            if isinstance(n, ast.Compare) and any(
                    isinstance(op, (ast.Is, ast.IsNot)) for op in n.ops):
                for sub in ast.walk(n):
                    skip.add(id(sub))
        for n in ast.walk(test):
            if id(n) in skip:
                continue
            if isinstance(n, ast.Name) and n.id in traced and \
                    isinstance(n.ctx, ast.Load):
                return n.id
        return None

    def _gl102_static_args(self, sf: SourceFile,
                           jit_targets: dict[str, _JitInfo]) -> None:
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            nm = _self_attr(node.func) or (
                node.func.id if isinstance(node.func, ast.Name) else None)
            info = jit_targets.get(nm or "")
            if info is None:
                continue
            for i, arg in enumerate(node.args):
                if i in info.static_nums and \
                        isinstance(arg, (ast.List, ast.Dict, ast.Set)):
                    self.findings.append(Finding(
                        sf.rel, arg.lineno, "GL102",
                        f"unhashable {type(arg).__name__.lower()} literal "
                        f"at static_argnums position {i} of jitted "
                        f"{nm} — raises on every call; pass a tuple"))
            for kw in node.keywords:
                if kw.arg in info.static_names and \
                        isinstance(kw.value, (ast.List, ast.Dict, ast.Set)):
                    self.findings.append(Finding(
                        sf.rel, kw.value.lineno, "GL102",
                        f"unhashable {type(kw.value).__name__.lower()} "
                        f"literal for static arg {kw.arg!r} of jitted "
                        f"{nm} — raises on every call; pass a tuple"))

    # -- GL103 -------------------------------------------------------------
    def _gl103(self, sf: SourceFile, jitted: list[_JitInfo]) -> None:
        module_containers = {
            t.id
            for node in sf.tree.body if isinstance(node, ast.Assign)
            for t in node.targets if isinstance(t, ast.Name)
            and isinstance(node.value, (ast.List, ast.Dict, ast.Set,
                                        ast.ListComp, ast.DictComp))
        }
        for info in jitted:
            if info.fn is None:
                continue
            globals_declared: set[str] = set()
            for node in ast.walk(info.fn):
                if isinstance(node, ast.Global):
                    globals_declared.update(node.names)
            for node in ast.walk(info.fn):
                if isinstance(node, (ast.Assign, ast.AugAssign)):
                    targets = (node.targets if isinstance(node, ast.Assign)
                               else [node.target])
                    for t in targets:
                        base = t.value if isinstance(t, ast.Subscript) else t
                        if isinstance(base, ast.Name) and (
                                base.id in globals_declared
                                or (isinstance(t, ast.Subscript)
                                    and base.id in module_containers)):
                            self.findings.append(Finding(
                                sf.rel, t.lineno, "GL103",
                                f"write to module global {base.id!r} "
                                f"inside jitted {info.fn.name} — leaks a "
                                f"tracer past the trace"))
                        a = _self_attr(base)
                        if a is not None:
                            self.findings.append(Finding(
                                sf.rel, t.lineno, "GL103",
                                f"write to self.{a} inside jitted "
                                f"{info.fn.name} — runs at trace time "
                                f"only and leaks a tracer"))
                if isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute) and \
                        node.func.attr in _MUTATORS and \
                        isinstance(node.func.value, ast.Name) and \
                        node.func.value.id in module_containers:
                    self.findings.append(Finding(
                        sf.rel, node.lineno, "GL103",
                        f"mutation of module container "
                        f"{node.func.value.id!r} inside jitted "
                        f"{info.fn.name} — leaks a tracer past the "
                        f"trace"))
