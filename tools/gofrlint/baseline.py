"""Baseline: accepted findings checked into the repo.

The baseline is a multiset of line-INDEPENDENT finding keys
(``path::code::message``) so edits above an accepted finding do not
churn entries. CI fails on BOTH directions of drift:

  - a current finding with no baseline entry  -> new (regression);
  - a baseline entry with no current finding  -> stale (the finding was
    fixed — delete the entry so it cannot mask a future regression).

``--write-baseline`` regenerates the file from the current findings.
"""

from __future__ import annotations

import json
from collections import Counter
from pathlib import Path

from .base import Finding

VERSION = 1


def load(path: Path) -> Counter:
    data = json.loads(path.read_text(encoding="utf-8"))
    if data.get("version") != VERSION:
        raise ValueError(f"unsupported baseline version: {data.get('version')}")
    return Counter(data.get("findings", {}))


def write(path: Path, findings: list[Finding]) -> None:
    counts = Counter(f.key() for f in findings)
    data = {
        "version": VERSION,
        "comment": "accepted gofrlint findings; regenerate with "
                   "`python -m tools.gofrlint --write-baseline`",
        "findings": {k: counts[k] for k in sorted(counts)},
    }
    path.write_text(json.dumps(data, indent=1) + "\n", encoding="utf-8")


def compare(findings: list[Finding], accepted: Counter
            ) -> tuple[list[Finding], list[str]]:
    """(new findings not in the baseline, stale baseline keys)."""
    remaining = Counter(accepted)
    new: list[Finding] = []
    for f in findings:
        if remaining[f.key()] > 0:
            remaining[f.key()] -= 1
        else:
            new.append(f)
    stale = sorted(k for k, n in remaining.items() if n > 0 for _ in range(n))
    return new, stale
