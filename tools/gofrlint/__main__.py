"""CLI: ``python -m tools.gofrlint [paths...]``.

Exit codes: 0 clean (or everything baselined), 1 new findings and/or
stale baseline entries, 2 usage error. With ``--stats`` the LAST stdout
line is a JSON summary (tools/README.md stdout contract: everything
above it is human-readable progress).
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import Counter
from pathlib import Path

from . import baseline as baseline_mod
from . import pass_of, run

REPO = Path(__file__).resolve().parent.parent.parent


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.gofrlint",
        description="multi-pass static analyzer (style + lock discipline "
                    "+ TPU hot-path + resources + distributed safety)")
    ap.add_argument("paths", nargs="*", type=Path,
                    help="files/dirs to analyze (default: the repo)")
    ap.add_argument("--select", action="append", default=None,
                    metavar="CODES",
                    help="comma-separated code prefixes (GL0,E501,...)")
    ap.add_argument("--baseline", type=Path, default=None,
                    help="accepted-findings file; fail only on new "
                         "findings and stale entries")
    ap.add_argument("--write-baseline", type=Path, default=None,
                    metavar="PATH",
                    help="write the current findings as the baseline "
                         "and exit 0")
    ap.add_argument("--stats", action="store_true",
                    help="emit a last-line JSON summary")
    args = ap.parse_args(argv)

    roots = args.paths or [REPO]
    select = None
    if args.select:
        select = {c.strip().upper()
                  for chunk in args.select for c in chunk.split(",")
                  if c.strip()}
    findings, n_files = run(roots, select)

    if args.write_baseline is not None:
        if select:
            # a select-filtered write would silently DROP every
            # accepted finding for the unselected codes
            print("gofrlint: refusing --write-baseline with --select "
                  "(the baseline must cover every code)", file=sys.stderr)
            return 2
        baseline_mod.write(args.write_baseline, findings)
        print(f"wrote {len(findings)} finding(s) to {args.write_baseline}")
        return 0

    new, stale = findings, []
    if args.baseline is not None:
        try:
            accepted = baseline_mod.load(args.baseline)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"gofrlint: cannot load baseline {args.baseline}: {e}",
                  file=sys.stderr)
            return 2
        if select:
            # --select filtered the findings, so entries for UNselected
            # codes must not read as stale (key: path::code::message)
            accepted = type(accepted)({
                k: v for k, v in accepted.items()
                if any(k.split("::")[1].startswith(s) for s in select)})
        new, stale = baseline_mod.compare(findings, accepted)

    for f in new:
        print(f)
    for key in stale:
        print(f"STALE baseline entry (finding fixed — delete it): {key}")
    failed = bool(new or stale)
    if not args.stats:
        print(f"{len(new)} new finding(s), {len(stale)} stale baseline "
              f"entr(ies), {n_files} file(s)", file=sys.stderr)
    else:
        by_code = Counter(f.code for f in findings)
        # per-pass breakdown: CI output must show WHICH pass regressed
        # (one aggregate bucket hides a resources regression behind a
        # style fix). Every pass always appears, zero or not, so a
        # pass silently dropping from the run is itself visible.
        by_pass = {name: {"findings": 0, "new": 0}
                   for name in ("style", "locks", "hotpath", "resources",
                                "dist")}
        for f in findings:
            by_pass[pass_of(f.code)]["findings"] += 1
        for f in new:
            by_pass[pass_of(f.code)]["new"] += 1
        print(json.dumps({
            "tool": "gofrlint",
            "files": n_files,
            "findings": len(findings),
            "new": len(new),
            "stale_baseline": len(stale),
            "baselined": len(findings) - len(new),
            "by_code": {k: by_code[k] for k in sorted(by_code)},
            "by_pass": by_pass,
            "ok": not failed,
        }, sort_keys=False))
    return 1 if failed else 0


if __name__ == "__main__":
    raise SystemExit(main())
