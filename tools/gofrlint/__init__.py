"""gofrlint: the repo's multi-pass static analyzer.

Reference parity: the reference GoFr CI blocks on golangci-lint and
`go test -race` (.github/workflows/go.yml:231-239). This package is the
Python equivalent, grown from the single-file tools/lint.py fallback
linter into three passes:

  style     — the original hermetic rule set (F401/F811/E501/E711/E722/
              B006/B011/F601/F541/W291/W191/T201/E999)
  locks     — GL001 unguarded writes to lock-guarded attributes,
              GL002 lock-acquisition-order cycles (potential deadlocks)
  hotpath   — GL101 host syncs inside decode/step/dispatch loops,
              GL102 jit recompile hazards, GL103 tracer leakage
  resources — GL201 use-after-donate, GL202 unaccounted device
              allocations, GL203 unbounded request-path container
              growth, GL204 fail-open OOM handling
  dist      — GL301 blocking calls under a held lock, GL302
              thread-lifecycle leaks (no close-path join), GL303
              unmapped wire failure paths (raw-500 class), GL304
              metric discipline (unregistered/dynamic names,
              inconsistent label keys)

Every rule honors `# noqa` / `# noqa: CODE` line suppression (applied
centrally). Accepted findings live in tools/gofrlint_baseline.json; CI
runs `python -m tools.gofrlint --baseline tools/gofrlint_baseline.json`
and fails on new findings AND on stale baseline entries. The runtime
complement (the lock-order watchdog that is this repo's `go test
-race`) is gofr_tpu/testutil/lockwatch.py, enabled over the threaded
tier-1 tests with `pytest --lockwatch`; the resources pass's runtime
complement is gofr_tpu/testutil/hbmwatch.py (`pytest --hbmwatch`), the
live-device-buffer leak harness.

See docs/advanced-guide/static-analysis.md for the rule catalog.
"""

from __future__ import annotations

from pathlib import Path

from . import dist, hotpath, locks, resources, style
from .base import Finding, SourceFile, collect_files

__all__ = ["Finding", "SourceFile", "collect_files", "pass_of", "run"]

# code -> pass, for the per-pass --stats breakdown (CI must see WHICH
# pass regressed, not one aggregate bucket)
_PASS_PREFIXES = (("GL0", "locks"), ("GL1", "hotpath"),
                  ("GL2", "resources"), ("GL3", "dist"))


def pass_of(code: str) -> str:
    for prefix, name in _PASS_PREFIXES:
        if code.startswith(prefix):
            return name
    return "style"

_REPO = Path(__file__).resolve().parent.parent.parent


def _rel(path: Path) -> str:
    """Repo-relative display/baseline path: keys in
    tools/gofrlint_baseline.json must not depend on where the checkout
    lives or the invoking cwd. Paths outside the repo stay as given."""
    try:
        return path.resolve().relative_to(_REPO).as_posix()
    except ValueError:
        return str(path)


def run(roots: list[Path], select: set[str] | None = None
        ) -> tuple[list[Finding], int]:
    """Run every pass over ``roots``. Returns (findings after noqa
    suppression, number of files analyzed). ``select`` limits output to
    the given codes (prefix match: "GL1" selects GL101/GL102/GL103)."""
    files = collect_files(roots)
    lock_pass = locks.LockPass()
    hot_pass = hotpath.HotPathPass()
    res_pass = resources.ResourcePass()
    dist_pass = dist.DistPass()
    findings: list[Finding] = []
    sources: dict[str, SourceFile] = {}
    for path in files:
        sf = SourceFile(path, _rel(path))
        sources[sf.rel] = sf
        findings.extend(style.run(sf))
        lock_pass.feed(sf)
        hot_pass.feed(sf)
        res_pass.feed(sf)
        dist_pass.feed(sf)
    findings.extend(lock_pass.finish())
    findings.extend(hot_pass.findings)
    findings.extend(res_pass.findings)
    # dist consumes the lock pass's post-fixpoint state: must run after
    findings.extend(dist_pass.finish(lock_pass))
    findings = [f for f in findings
                if f.path not in sources
                or not sources[f.path].suppressed(f)]
    if select:
        findings = [f for f in findings
                    if any(f.code.startswith(s) for s in select)]
    findings.sort(key=lambda f: (f.path, f.line, f.code, f.msg))
    return findings, len(files)
