"""Resource-lifetime pass: device-memory and buffer-lifetime rules
(GL2xx). The static half of the HBM accounting substrate the unified
memory arbiter will be built on (ROADMAP top item); the runtime half is
gofr_tpu/testutil/hbmwatch.py.

GL201 — scope ``gofr_tpu/``. Use-after-donate: an argument passed at a
donated position of a ``jax.jit(..., donate_argnums=...)`` call site is
read, returned, or stored again AFTER the call. Donation invalidates
the buffer — JAX raises on access at best, and on some backends the
aliased memory is silently reused by the jit's outputs. The dataflow
runs over the enclosing function in statement order with
rebinding-kills: assigning the name (``self.cache = step(self.cache,
...)`` rebinds in the same statement and is clean) clears the taint;
loop bodies are analyzed twice so a donation in iteration N is seen by
a read in iteration N+1. Metadata reads (``.shape``/``.dtype``/
``.ndim``/``.nbytes``) survive donation (the aval outlives the buffer)
and are exempt, as is any line annotated ``# gl: consumed`` — the
escape hatch for flows the analyzer cannot see (e.g. a conditional
donation the caller re-checks).

GL202 — scope ``gofr_tpu/tpu/`` (the serving modules). Unaccounted
device allocations: a ``jnp.zeros/ones/full/empty[_like]``,
``jax.device_put``, or pool-row construction (``*init_cache`` /
``init_paged_cache`` / ``init_lora``) whose result is PERSISTED on the
instance (assigned to ``self.X`` directly, or via locals that later
flow into a ``self.X`` assignment) without flowing through the
accounting API (an ``hbm.account(...)`` or ``hbm.alloc(...)`` —
the arbiter's reclaim-then-retry lease form — wrapping the allocation
or its local). Transient allocations that die with the function are not
flagged — persistent buffers are exactly the arbiter's future lease
targets, and an allocation the registry cannot see is capacity the
arbiter cannot rebalance (the RESOURCE_EXHAUSTED cascade in
BENCH_CANDIDATE.json). Allocations inside jit-traced functions are
traced, not eager HBM, and are exempt.

GL203 — scope ``gofr_tpu/tpu/``. Unbounded request-path growth: an
append/insert into an instance- or module-level container from a
request/decode-path method (anything not construction/teardown) in a
class that contains NO eviction for that container — no pop/remove/
clear/del, no non-constructor reassignment. This is the leak shape
that killed the flat prefix cache: every request adds an entry, nothing
ever removes one, and steady-state HBM/host growth ends in
RESOURCE_EXHAUSTED.

GL204 — scope ``gofr_tpu/``. Fail-open OOM handling: an ``except`` arm
that names an OOM-class exception (``XlaRuntimeError``,
``ResourceExhausted*``, ``OutOfMemory*``, the arbiter's
``HBMExhausted``) — or string-matches
``RESOURCE_EXHAUSTED`` / ``out of memory`` inside a generic handler —
and neither re-raises nor routes to the admission-shed path
(``raise``, a ``*shed*``/``*admit*`` call, ``TooManyRequests``).
Swallowing OOM turns memory pressure into silent capacity loss; the
overload-safe answer is the AdmissionGate shed path (resilience.py).
"""

from __future__ import annotations

import ast
import re

from .base import Finding, SourceFile, _self_attr, in_framework, \
    project_parts
from .hotpath import _callee_last, _callee_root

# allocation constructors whose results are eager device buffers
_ALLOC_JNP = {"zeros", "ones", "full", "empty",
              "zeros_like", "ones_like", "full_like", "empty_like"}
_ALLOC_ANY = {"device_put"}
_ALLOC_SUBSTR = ("init_cache", "init_paged_cache", "init_lora")
# the declared accounting API (gofr_tpu/tpu/hbm.py): account() records
# post-hoc; alloc()/lease() are the arbiter's budgeted forms (lease +
# reclaim-then-retry + account), and alloc_sharded() is the PER-SHARD
# variant mesh engines use (per-device lease split + per-shard
# account) — all three match only as QUALIFIED hbm.alloc/hbm.lease/
# hbm.alloc_sharded (see _is_account_call): "alloc" is far too
# generic a method name to bless bare (the paged engine's block
# allocator is literally self._alloc.alloc)
_ACCOUNT_FNS = {"account"}
_ARBITER_FNS = {"alloc", "lease", "alloc_sharded", "tenant_lease"}


def _is_account_call(func) -> bool:
    last = _callee_last(func)
    if last in _ACCOUNT_FNS:
        return True
    if last in _ARBITER_FNS:
        return _callee_root(func) == "hbm"
    return False
# attribute reads that survive donation (metadata lives on the aval)
_META_ATTRS = {"shape", "dtype", "ndim", "size", "nbytes", "sharding",
               "quantized"}
# construction/teardown methods: allocations and container writes here
# are setup, not request-path growth
_SETUP_NAMES = {"__init__", "__post_init__", "__del__", "close", "clear",
                "reset", "drain", "warmup", "stop", "shutdown"}
_GROW_CALLS = {"append", "add", "insert", "extend", "appendleft",
               "setdefault"}
_SHRINK_CALLS = {"pop", "popitem", "popleft", "remove", "discard",
                 "clear"}
_CONTAINER_CTORS = {"list", "dict", "set", "deque", "defaultdict",
                    "OrderedDict", "Counter"}
_OOM_TYPE_SUBSTR = ("XlaRuntimeError", "ResourceExhausted", "OutOfMemory",
                    "HBMExhausted")
_OOM_STR_RE = re.compile(r"RESOURCE_EXHAUSTED|out of memory",
                         re.IGNORECASE)
_SHED_SUBSTR = ("shed", "admit", "TooManyRequests")
_GL_CONSUMED_RE = re.compile(r"#\s*gl:\s*consumed\b")


def _donate_spec(call: ast.Call) -> tuple[set[int], set[str]]:
    """donate_argnums/donate_argnames of one jit(...) call."""
    nums: set[int] = set()
    names: set[str] = set()
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            elts = [kw.value]
            if isinstance(kw.value, (ast.Tuple, ast.List)):
                elts = list(kw.value.elts)
            for e in elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, int):
                    nums.add(e.value)
        if kw.arg == "donate_argnames" and isinstance(
                kw.value, (ast.Tuple, ast.List, ast.Constant)):
            elts = kw.value.elts if not isinstance(kw.value, ast.Constant) \
                else [kw.value]
            for e in elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    names.add(e.value)
    return nums, names


def _is_jit_name(node: ast.expr) -> bool:
    return _callee_last(node) == "jit"


def _bind_name(node: ast.expr) -> str | None:
    """Callable identity at a call/assignment site: ``self._step_jit``
    and ``step_jit`` both key as their last name — donation info and
    call sites must agree whether the wrapper lives on self or a
    local/module binding."""
    return _self_attr(node) or (
        node.id if isinstance(node, ast.Name) else None)


def _collect_donors(tree: ast.AST) -> dict[str, tuple[set[int], set[str]]]:
    """name -> (donated positions, donated kwarg names) for every
    callable this module binds to a donating jit."""
    donors: dict[str, tuple[set[int], set[str]]] = {}

    def add(nm: str | None, nums: set[int], names: set[str]) -> None:
        if nm is None or not (nums or names):
            return
        have = donors.setdefault(nm, (set(), set()))
        have[0].update(nums)
        have[1].update(names)

    for node in ast.walk(tree):
        # X = jax.jit(fn, donate_argnums=...)  (optionally nested in
        # other calls on the RHS — rare, keep the direct form only)
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Call) and \
                _is_jit_name(node.value.func) and node.value.args:
            nums, names = _donate_spec(node.value)
            for t in node.targets:
                add(_bind_name(t), nums, names)
        # @jax.jit(donate_argnums=...) / @partial(jax.jit, donate...)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if not isinstance(dec, ast.Call):
                    continue
                if _is_jit_name(dec.func) or (
                        _callee_last(dec.func) == "partial" and dec.args
                        and _is_jit_name(dec.args[0])):
                    nums, names = _donate_spec(dec)
                    add(node.name, nums, names)
    return donors


# -- GL201: use-after-donate dataflow ----------------------------------------

# taint variables: ("l", name) for locals, ("s", attr) for self.X
_Var = tuple[str, str]


def _var_of(node: ast.expr) -> _Var | None:
    a = _self_attr(node)
    if a is not None:
        return ("s", a)
    if isinstance(node, ast.Name):
        return ("l", node.id)
    return None


def _var_disp(v: _Var) -> str:
    return f"self.{v[1]}" if v[0] == "s" else v[1]


class _DonateFlow:
    """Statement-ordered taint propagation for one function body."""

    def __init__(self, sf: SourceFile, fn: ast.AST,
                 donors: dict[str, tuple[set[int], set[str]]],
                 out: list[Finding]):
        self.sf = sf
        self.fn = fn
        self.donors = donors
        self.out = out
        self._seen: set[tuple[int, _Var]] = set()

    # -- expression-level helpers -------------------------------------------
    def _donations(self, stmt: ast.stmt) -> list[tuple[_Var, ast.Call]]:
        """(var, call) for every Name/self-attr passed at a donated
        position of a donating callable anywhere in ``stmt``."""
        found: list[tuple[_Var, ast.Call]] = []
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Call):
                continue
            spec = self.donors.get(_bind_name(node.func) or "")
            if spec is None:
                continue
            nums, names = spec
            for i, arg in enumerate(node.args):
                if i in nums:
                    v = _var_of(arg)
                    if v is not None:
                        found.append((v, node))
            for kw in node.keywords:
                if kw.arg in names:
                    v = _var_of(kw.value)
                    if v is not None:
                        found.append((v, node))
        return found

    def _reads(self, node: ast.AST) -> list[tuple[_Var, int]]:
        """Every (var, line) read in ``node``, metadata reads pruned."""
        skip: set[int] = set()
        for n in ast.walk(node):
            if isinstance(n, ast.Attribute) and n.attr in _META_ATTRS:
                for sub in ast.walk(n):
                    skip.add(id(sub))
        out: list[tuple[_Var, int]] = []
        for n in ast.walk(node):
            if id(n) in skip:
                continue
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
                out.append((("l", n.id), n.lineno))
            else:
                a = _self_attr(n)
                if a is not None and isinstance(n.ctx, ast.Load):
                    out.append((("s", a), n.lineno))
        return out

    def _check_reads(self, node: ast.AST, taint: dict[_Var, int]) -> None:
        for v, line in self._reads(node):
            dline = taint.get(v)
            if dline is None or (line, v) in self._seen:
                continue
            if _GL_CONSUMED_RE.search(self.sf.comments.get(line, "")):
                continue
            self._seen.add((line, v))
            self.out.append(Finding(
                self.sf.rel, line, "GL201",
                f"{_var_disp(v)} used after being donated at line "
                f"{dline} in {self.fn.name} — the donated buffer is "
                f"invalidated; rebind the jit's output (or annotate "
                f"`# gl: consumed`)"))

    def _kills(self, target: ast.expr, taint: dict[_Var, int]) -> None:
        stack = [target]
        while stack:
            t = stack.pop()
            if isinstance(t, (ast.Tuple, ast.List)):
                stack.extend(t.elts)
                continue
            if isinstance(t, ast.Starred):
                stack.append(t.value)
                continue
            v = _var_of(t)
            if v is not None:
                taint.pop(v, None)

    # -- statement walk ------------------------------------------------------
    def exec_stmts(self, stmts: list[ast.stmt],
                   taint: dict[_Var, int]) -> dict[_Var, int]:
        for s in stmts:
            taint = self.exec_stmt(s, taint)
        return taint

    def exec_stmt(self, s: ast.stmt,
                  taint: dict[_Var, int]) -> dict[_Var, int]:
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef)):
            return taint  # nested scopes: analyzed on their own
        if isinstance(s, ast.If):
            self._check_reads(s.test, taint)
            t1 = self.exec_stmts(s.body, dict(taint))
            t2 = self.exec_stmts(s.orelse, dict(taint))
            return {**t1, **t2}
        if isinstance(s, (ast.For, ast.AsyncFor)):
            self._check_reads(s.iter, taint)
            self._kills(s.target, taint)
            t1 = self.exec_stmts(s.body, dict(taint))
            # second pass: loop-carried taint (donated in iteration N,
            # read in N+1); _seen dedupes the re-walk
            t2 = self.exec_stmts(s.body, {**taint, **t1})
            merged = {**taint, **t2}
            return self.exec_stmts(s.orelse, merged)
        if isinstance(s, ast.While):
            self._check_reads(s.test, taint)
            t1 = self.exec_stmts(s.body, dict(taint))
            self._check_reads(s.test, t1)
            t2 = self.exec_stmts(s.body, {**taint, **t1})
            merged = {**taint, **t2}
            return self.exec_stmts(s.orelse, merged)
        if isinstance(s, ast.Try):
            t_body = self.exec_stmts(s.body, dict(taint))
            merged = {**taint, **t_body}
            for h in s.handlers:
                merged = {**merged, **self.exec_stmts(h.body, dict(merged))}
            merged = self.exec_stmts(s.orelse, merged)
            return self.exec_stmts(s.finalbody, merged)
        if isinstance(s, (ast.With, ast.AsyncWith)):
            for item in s.items:
                self._check_reads(item.context_expr, taint)
                if item.optional_vars is not None:
                    self._kills(item.optional_vars, taint)
            return self.exec_stmts(s.body, taint)

        # simple statement: reads checked against PRE-state, then the
        # statement's own donations taint, then assignment targets kill
        # (targets bind the jit's OUTPUT — `x = step(x)` is clean)
        self._check_reads(s, taint)
        new_taint = [(v, call.lineno) for v, call in self._donations(s)]
        for v, line in new_taint:
            taint[v] = line
        if isinstance(s, ast.Assign):
            for t in s.targets:
                self._kills(t, taint)
        elif isinstance(s, (ast.AugAssign, ast.AnnAssign)):
            self._kills(s.target, taint)
        elif isinstance(s, ast.Delete):
            for t in s.targets:
                self._kills(t, taint)
        return taint


# -- GL202 helpers -----------------------------------------------------------

def _is_alloc(call: ast.Call) -> bool:
    last = _callee_last(call.func)
    if last is None:
        return False
    if last in _ALLOC_ANY:
        return True
    if any(sub in last for sub in _ALLOC_SUBSTR):
        return True
    return last in _ALLOC_JNP and _callee_root(call.func) == "jnp"


def _flat_stmts(body: list[ast.stmt]) -> list[ast.stmt]:
    """Statements of a function in source order, compound bodies
    flattened (GL202's local-flow scan only needs lexical order)."""
    out: list[ast.stmt] = []
    for s in body:
        out.append(s)
        for attr in ("body", "orelse", "finalbody"):
            out.extend(_flat_stmts(getattr(s, attr, []) or []))
        for h in getattr(s, "handlers", []) or []:
            out.extend(_flat_stmts(h.body))
    return [s for s in out
            if not isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef))]


# calls the allocated buffer flows THROUGH unchanged: the result still
# holds (or aliases) the allocation, so persistence propagates across
# them — unlike a dispatch call, which consumes its operands
_PASSTHROUGH = {"block_until_ready", "device_put"} | _ACCOUNT_FNS


def _persist_roots(value: ast.expr) -> set[int]:
    """ids of nodes in 'persisted position' of a value expression: the
    root, descending through pass-through wrappers and container
    displays. An allocation that only appears as an operand of some
    OTHER call (e.g. a padded-tokens buffer fed to a dispatch) is
    consumed by that call, not persisted by the assignment."""
    out: set[int] = set()
    stack = [value]
    while stack:
        n = stack.pop()
        out.add(id(n))
        if isinstance(n, ast.Call) and \
                _callee_last(n.func) in _PASSTHROUGH:
            stack.extend(n.args)
            stack.extend(kw.value for kw in n.keywords)
        elif isinstance(n, (ast.Tuple, ast.List, ast.Set)):
            stack.extend(n.elts)
        elif isinstance(n, ast.Dict):
            stack.extend(v for v in n.values if v is not None)
        elif isinstance(n, ast.Starred):
            stack.append(n.value)
        elif isinstance(n, ast.NamedExpr):
            stack.append(n.value)
        elif isinstance(n, ast.IfExp):
            stack.extend((n.body, n.orelse))
    return out


def _account_wraps(stmt: ast.stmt, node: ast.Call) -> bool:
    """Is ``node`` (an allocation) nested inside an account(...) call
    within its own statement?"""
    for n in ast.walk(stmt):
        if isinstance(n, ast.Call) and _is_account_call(n.func):
            if any(sub is node for sub in ast.walk(n)):
                return True
    return False


# -- the pass ----------------------------------------------------------------

class ResourcePass:
    def __init__(self):
        self.findings: list[Finding] = []

    def feed(self, sf: SourceFile) -> None:
        if sf.tree is None or not in_framework(sf.path):
            return
        donors = _collect_donors(sf.tree)
        jit_ids = self._jit_fn_ids(sf.tree, donors)
        # serving-module scope = gofr_tpu/tpu/ — the transport
        # (wire.py & co.) lives outside tpu/ and is excluded by the
        # path test alone
        in_tpu = "tpu" in project_parts(sf.path)
        for node in ast.walk(sf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if id(node) in jit_ids:
                    continue  # traced code: donation/allocation rules
                    # apply to the HOST side only
                if donors:
                    flow = _DonateFlow(sf, node, donors, self.findings)
                    flow.exec_stmts(list(node.body), {})
                if in_tpu:
                    self._gl202_fn(sf, node)
        if in_tpu:
            self._gl203(sf, jit_ids)
        self._gl204(sf)

    def _jit_fn_ids(self, tree: ast.AST,
                    donors: dict[str, tuple[set[int], set[str]]]
                    ) -> set[int]:
        """ids of function defs that are jit-traced (decorated, or
        wrapped by a jax.jit(fn) assignment anywhere in the module)."""
        ids: set[int] = set()
        defs = {n.name: n for n in ast.walk(tree)
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if _is_jit_name(dec):
                        ids.add(id(node))
                    elif isinstance(dec, ast.Call) and (
                            _is_jit_name(dec.func)
                            or (_callee_last(dec.func) == "partial"
                                and dec.args
                                and _is_jit_name(dec.args[0]))):
                        ids.add(id(node))
            if isinstance(node, ast.Call) and _is_jit_name(node.func) \
                    and node.args:
                fn = defs.get(_callee_last(node.args[0]) or "")
                if fn is not None:
                    ids.add(id(fn))
        return ids

    # -- GL202 ---------------------------------------------------------------
    def _gl202_fn(self, sf: SourceFile, fn: ast.AST) -> None:
        stmts = _flat_stmts(list(fn.body))
        for si, stmt in enumerate(stmts):
            if not isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                continue
            targets = stmt.targets if isinstance(stmt, ast.Assign) \
                else [stmt.target]
            value = stmt.value
            if value is None:
                continue
            proots = _persist_roots(value)
            allocs = [n for n in ast.walk(value)
                      if isinstance(n, ast.Call) and _is_alloc(n)
                      and id(n) in proots]
            if not allocs:
                continue
            self_attr: str | None = None
            locals_: set[str] = set()
            for t in targets:
                for tt in ast.walk(t):
                    a = _self_attr(tt)
                    if a is not None:
                        self_attr = a
                    elif isinstance(tt, ast.Name) and \
                            isinstance(tt.ctx, ast.Store):
                        locals_.add(tt.id)
            for alloc in allocs[:1]:  # one finding per statement
                if _account_wraps(stmt, alloc):
                    continue
                if self_attr is not None:
                    self._flag_202(sf, alloc, fn,
                                   f"self.{self_attr}")
                    continue
                if not locals_:
                    continue  # transient: consumed by this statement
                persisted = self._local_persists(stmts[si + 1:], locals_)
                if persisted is not None:
                    self._flag_202(sf, alloc, fn, persisted)

    def _local_persists(self, later: list[ast.stmt],
                        derived: set[str]) -> str | None:
        """Follow a local allocation through later statements: flowing
        into an account(...) call clears it; flowing into a self.X
        assignment persists it. Returns the persisting `self.X` (or
        None when the allocation stays function-local / accounted)."""
        derived = set(derived)
        for stmt in later:
            if not isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.Expr)):
                continue
            value = getattr(stmt, "value", None)
            if value is None:
                continue
            for n in ast.walk(value):
                if isinstance(n, ast.Call) and \
                        _is_account_call(n.func) and any(
                            isinstance(sub, ast.Name)
                            and sub.id in derived
                            for sub in ast.walk(n)):
                    return None  # flowed through the accounting API
            # the name persists/propagates only when it sits in a
            # persisted position of the value (pass-through wrappers /
            # container displays) — feeding it to a dispatch consumes it
            proots = _persist_roots(value)
            touches = any(isinstance(n, ast.Name) and n.id in derived
                          and isinstance(n.ctx, ast.Load)
                          and id(n) in proots
                          for n in ast.walk(value))
            if not touches:
                continue
            targets = stmt.targets if isinstance(stmt, ast.Assign) else \
                [stmt.target] if isinstance(stmt, ast.AnnAssign) else []
            for t in targets:
                for tt in ast.walk(t):
                    a = _self_attr(tt)
                    if a is not None:
                        return f"self.{a}"
                    if isinstance(tt, ast.Name) and \
                            isinstance(tt.ctx, ast.Store):
                        derived.add(tt.id)
        return None

    def _flag_202(self, sf: SourceFile, alloc: ast.Call, fn: ast.AST,
                  target: str) -> None:
        name = _callee_last(alloc.func)
        self.findings.append(Finding(
            sf.rel, alloc.lineno, "GL202",
            f"device allocation {name}() persisted to {target} in "
            f"{fn.name} without flowing through hbm.account() — "
            f"unaccounted HBM is invisible to the memory arbiter"))

    # -- GL203 ---------------------------------------------------------------
    def _gl203(self, sf: SourceFile, jit_ids: set[int]) -> None:
        # jit-traced functions are excluded: a container write there is
        # a TRACED write — GL103's territory, and reporting it twice
        # would double-bill one defect
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ClassDef):
                self._gl203_class(sf, node, jit_ids)
        self._gl203_module(sf, jit_ids)

    def _container_attrs(self, cls: ast.ClassDef) -> set[str]:
        out: set[str] = set()
        for node in ast.walk(cls):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            value = getattr(node, "value", None)
            is_container = isinstance(value, (ast.List, ast.Dict, ast.Set,
                                              ast.ListComp, ast.DictComp,
                                              ast.SetComp)) or (
                isinstance(value, ast.Call)
                and _callee_last(value.func) in _CONTAINER_CTORS)
            if not is_container:
                continue
            if (isinstance(value, ast.Call)
                    and _callee_last(value.func) == "deque"
                    and any(kw.arg == "maxlen"
                            and not (isinstance(kw.value, ast.Constant)
                                     and kw.value.value is None)
                            for kw in value.keywords)):
                # deque(maxlen=N) is a bounded ring: append() evicts
                # from the head once full — growth there is not a leak
                continue
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                a = _self_attr(t)
                if a is not None:
                    out.add(a)
        return out

    def _is_const_reset(self, value: ast.expr) -> bool:
        """`self.X[i] = []` / `= None` / `= 0` resets a cell — eviction
        shape, not growth."""
        if isinstance(value, ast.Constant):
            return True
        return isinstance(value, (ast.List, ast.Dict, ast.Set)) and \
            not getattr(value, "elts", None) and \
            not getattr(value, "keys", None)

    def _gl203_class(self, sf: SourceFile, cls: ast.ClassDef,
                     jit_ids: set[int]) -> None:
        attrs = self._container_attrs(cls)
        if not attrs:
            return
        shrunk: set[str] = set()
        grow: list[tuple[str, int, str]] = []  # (attr, line, method)
        for m in cls.body:
            if not isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    or id(m) in jit_ids:
                continue
            setup = m.name in _SETUP_NAMES or \
                m.name.lstrip("_").startswith(("evict", "invalidate",
                                               "retire", "reap", "prune",
                                               "expire", "trim", "load_",
                                               "register"))
            for node in ast.walk(m):
                # X.pop()/remove()/clear() — eviction anywhere counts
                if isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute):
                    base = _self_attr(node.func.value)
                    if isinstance(node.func.value, ast.Subscript):
                        base = _self_attr(node.func.value.value)
                    if base in attrs:
                        if node.func.attr in _SHRINK_CALLS:
                            shrunk.add(base)
                        elif node.func.attr in _GROW_CALLS and not setup:
                            grow.append((base, node.lineno, m.name))
                if isinstance(node, ast.Delete):
                    for t in node.targets:
                        if isinstance(t, ast.Subscript):
                            base = _self_attr(t.value)
                            if base in attrs:
                                shrunk.add(base)
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        a = _self_attr(t)
                        if a in attrs and m.name != "__init__":
                            shrunk.add(a)  # wholesale reassignment
                        if isinstance(t, ast.Subscript):
                            base = _self_attr(t.value)
                            if base in attrs:
                                if self._is_const_reset(node.value):
                                    shrunk.add(base)
                                elif not setup:
                                    grow.append((base, t.lineno, m.name))
        for attr, line, meth in grow:
            if attr in shrunk:
                continue
            self.findings.append(Finding(
                sf.rel, line, "GL203",
                f"self.{attr} grows in request-path method {meth} and "
                f"the class never evicts from it — unbounded steady-"
                f"state growth (the flat-prefix-cache leak shape)"))

    def _gl203_module(self, sf: SourceFile, jit_ids: set[int]) -> None:
        containers = {
            t.id
            for node in sf.tree.body if isinstance(node, ast.Assign)
            for t in node.targets if isinstance(t, ast.Name)
            and (isinstance(node.value, (ast.List, ast.Dict, ast.Set,
                                         ast.ListComp, ast.DictComp))
                 or (isinstance(node.value, ast.Call)
                     and _callee_last(node.value.func)
                     in _CONTAINER_CTORS))
        }
        if not containers:
            return
        shrunk: set[str] = set()
        grow: list[tuple[str, int, str]] = []
        for node in ast.walk(sf.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)) \
                    or id(node) in jit_ids:
                continue
            setup = node.name in _SETUP_NAMES
            for n in ast.walk(node):
                if isinstance(n, ast.Call) and \
                        isinstance(n.func, ast.Attribute) and \
                        isinstance(n.func.value, ast.Name) and \
                        n.func.value.id in containers:
                    if n.func.attr in _SHRINK_CALLS:
                        shrunk.add(n.func.value.id)
                    elif n.func.attr in _GROW_CALLS and not setup:
                        grow.append((n.func.value.id, n.lineno, node.name))
                if isinstance(n, ast.Delete):
                    for t in n.targets:
                        if isinstance(t, ast.Subscript) and \
                                isinstance(t.value, ast.Name) and \
                                t.value.id in containers:
                            shrunk.add(t.value.id)
                if isinstance(n, ast.Assign):
                    for t in n.targets:
                        if isinstance(t, ast.Subscript) and \
                                isinstance(t.value, ast.Name) and \
                                t.value.id in containers and not setup \
                                and not self._is_const_reset(n.value):
                            grow.append((t.value.id, t.lineno, node.name))
        for name, line, meth in grow:
            if name in shrunk:
                continue
            self.findings.append(Finding(
                sf.rel, line, "GL203",
                f"module container {name!r} grows in {meth} and nothing "
                f"in this module ever evicts from it — unbounded "
                f"steady-state growth"))

    # -- GL204 ---------------------------------------------------------------
    def _names_oom_type(self, type_node: ast.expr | None) -> bool:
        if type_node is None:
            return False
        for n in ast.walk(type_node):
            last = _callee_last(n) if isinstance(
                n, (ast.Attribute, ast.Name)) else None
            if last and any(sub in last for sub in _OOM_TYPE_SUBSTR):
                return True
        return False

    def _handles_oom(self, body: list[ast.stmt]) -> bool:
        """Does this block rethrow or route to the shed path?"""
        for s in body:
            for n in ast.walk(s):
                if isinstance(n, ast.Raise):
                    return True
                if isinstance(n, ast.Call):
                    last = _callee_last(n.func) or ""
                    if any(sub.lower() in last.lower()
                           for sub in _SHED_SUBSTR):
                        return True
                if isinstance(n, ast.Name) and any(
                        sub in n.id for sub in ("TooManyRequests",)):
                    return True
        return False

    def _gl204(self, sf: SourceFile) -> None:
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if self._names_oom_type(node.type):
                if not self._handles_oom(node.body):
                    self.findings.append(Finding(
                        sf.rel, node.lineno, "GL204",
                        "OOM-class exception swallowed without rethrow "
                        "or admission-shed routing — fail-open OOM "
                        "handling turns memory pressure into silent "
                        "capacity loss"))
                continue
            # generic handler string-matching RESOURCE_EXHAUSTED: the
            # matching If arm must rethrow or shed
            for n in ast.walk(node):
                if not isinstance(n, ast.If):
                    continue
                has_oom_str = any(
                    isinstance(c, ast.Constant)
                    and isinstance(c.value, str)
                    and _OOM_STR_RE.search(c.value)
                    for c in ast.walk(n.test))
                if has_oom_str and not self._handles_oom(n.body):
                    self.findings.append(Finding(
                        sf.rel, n.lineno, "GL204",
                        "RESOURCE_EXHAUSTED matched and swallowed "
                        "without rethrow or admission-shed routing — "
                        "fail-open OOM handling turns memory pressure "
                        "into silent capacity loss"))
