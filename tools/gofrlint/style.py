"""Style/correctness pass: the original hermetic-linter rule set.

  F401  unused import (module scope; __init__.py re-exports exempt)
  F811  redefinition of a top-level def/class by another def/class
  E501  line longer than MAX_LINE columns
  E711  comparison to None with ==/!=
  E722  bare `except:`
  B006  mutable default argument (list/dict/set literal or call)
  B011  assert on a non-empty tuple literal (always true)
  F601  duplicate literal key in a dict display
  F541  f-string without any placeholder
  W291  trailing whitespace / W191 tab indentation
  T201  bare `print(` inside gofr_tpu/ — framework output must go
        through glog so every line carries trace correlation; CLI
        command output may opt out with `# noqa: T201`
  E999  syntax error

Findings are emitted UNFILTERED; `# noqa` suppression happens once, in
the runner (base.SourceFile.suppressed), for every rule alike.
"""

from __future__ import annotations

import ast
import re

from .base import MAX_LINE, Finding, SourceFile, in_framework


def _is_mutable_default(node: ast.expr) -> bool:
    if isinstance(node, (ast.List, ast.Dict, ast.Set)):
        return True
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
        return node.func.id in {"list", "dict", "set", "bytearray"}
    return False


class Checker(ast.NodeVisitor):
    """AST-level style checks. The constructor signature is stable API:
    tools/lint.py (the CI fallback shim) and its tests build Checkers
    directly."""

    def __init__(self, path: str, tree: ast.AST, is_init: bool,
                 source: str, in_framework: bool = False):
        self.path = path
        self.is_init = is_init
        self.in_framework = in_framework  # file lives under gofr_tpu/
        self.findings: list[Finding] = []
        self.imported: dict[str, int] = {}       # name -> lineno
        self.used: set[str] = set()
        self.dunder_all: set[str] = set()
        self._toplevel_defs: dict[str, int] = {}
        self._source = source
        self._in_format_spec = False
        self.visit(tree)

    def add(self, node, code, msg):
        self.findings.append(Finding(self.path, node.lineno, code, msg))

    # -- imports ----------------------------------------------------------
    def _record_import(self, alias: ast.alias, node):
        name = alias.asname or alias.name.split(".")[0]
        if name == "*":
            return
        # "import x as x" / "from y import x as x" is the PEP 484
        # re-export idiom — exempt, like ruff's F401 convention
        if alias.asname is not None and alias.asname == alias.name:
            return
        self.imported[name] = node.lineno

    def visit_Import(self, node):
        for a in node.names:
            self._record_import(a, node)

    def visit_ImportFrom(self, node):
        if node.module == "__future__":
            return
        for a in node.names:
            self._record_import(a, node)

    # -- usages -----------------------------------------------------------
    def visit_Name(self, node):
        if isinstance(node.ctx, ast.Load):
            self.used.add(node.id)
        self.generic_visit(node)

    def visit_Assign(self, node):
        for t in node.targets:
            if isinstance(t, ast.Name) and t.id == "__all__" and \
                    isinstance(node.value, (ast.List, ast.Tuple)):
                for elt in node.value.elts:
                    if isinstance(elt, ast.Constant) and \
                            isinstance(elt.value, str):
                        self.dunder_all.add(elt.value)
        self.generic_visit(node)

    # -- defs -------------------------------------------------------------
    def _check_defaults(self, node):
        for d in list(node.args.defaults) + [
                d for d in node.args.kw_defaults if d is not None]:
            if _is_mutable_default(d):
                self.add(d, "B006",
                         "mutable default argument (shared across calls)")

    def _check_redef(self, node):
        # only flag UNdecorated def/class shadowing another at the SAME
        # module top level — decorators (@overload, @singledispatch
        # registrations, property setters) legitimately re-bind a name
        if node.col_offset != 0 or node.decorator_list:
            return
        prev = self._toplevel_defs.get(node.name)
        if prev is not None:
            self.add(node, "F811",
                     f"redefinition of {node.name!r} from line {prev}")
        self._toplevel_defs[node.name] = node.lineno

    def visit_FunctionDef(self, node):
        self._check_defaults(node)
        self._check_redef(node)
        self.generic_visit(node)

    def visit_AsyncFunctionDef(self, node):
        self._check_defaults(node)
        self._check_redef(node)
        self.generic_visit(node)

    def visit_ClassDef(self, node):
        self._check_redef(node)
        self.generic_visit(node)

    def visit_Call(self, node):
        # T201: framework code must log through glog (trace-correlated
        # structured lines), never print to raw stdout/stderr. CLI
        # command OUTPUT — the command's product, not logging — opts
        # out per line with `# noqa: T201` (central suppression).
        if self.in_framework and isinstance(node.func, ast.Name) \
                and node.func.id == "print":
            self.add(node, "T201",
                     "bare print() in framework code; use glog (or "
                     "`# noqa: T201` for CLI command output)")
        self.generic_visit(node)

    # -- misc -------------------------------------------------------------
    def visit_Compare(self, node):
        for op, comp in zip(node.ops, node.comparators):
            if isinstance(op, (ast.Eq, ast.NotEq)) and \
                    isinstance(comp, ast.Constant) and comp.value is None:
                self.add(node, "E711", "comparison to None; use `is None`")
        self.generic_visit(node)

    def visit_ExceptHandler(self, node):
        if node.type is None:
            self.add(node, "E722", "bare `except:`; catch something")
        self.generic_visit(node)

    def visit_Assert(self, node):
        if isinstance(node.test, ast.Tuple) and node.test.elts:
            self.add(node, "B011", "assert on a tuple is always true")
        self.generic_visit(node)

    def visit_Dict(self, node):
        seen: dict[object, int] = {}
        for k in node.keys:
            if isinstance(k, ast.Constant):
                try:
                    key = (type(k.value).__name__, k.value)
                except TypeError:
                    continue
                if key in seen:
                    self.add(k, "F601",
                             f"duplicate dict key {k.value!r} "
                             f"(first at line {seen[key]})")
                else:
                    seen[key] = k.lineno
        self.generic_visit(node)

    def visit_JoinedStr(self, node):
        # F541 is suppressed inside a format spec: `{x:.2f}` parses as a
        # nested placeholder-less JoinedStr there, which is not an
        # f-string the author wrote
        if not self._in_format_spec and \
                not any(isinstance(v, ast.FormattedValue) for v in node.values):
            self.add(node, "F541", "f-string without placeholders")
        self.generic_visit(node)

    def visit_FormattedValue(self, node):
        self.visit(node.value)
        if node.format_spec is not None:
            # names inside nested format specs (f"{x:{width}}") are real
            # usages — F401 must see them; only the F541 check is muted
            prev = self._in_format_spec
            self._in_format_spec = True
            try:
                self.visit(node.format_spec)
            finally:
                self._in_format_spec = prev

    # -- finish -----------------------------------------------------------
    def finish(self):
        if self.is_init:
            return  # __init__.py imports are the public re-export surface
        for name, line in self.imported.items():
            if name in self.used or name in self.dunder_all:
                continue
            # a bare name can still be referenced from a doctest or
            # __getattr__ string table — only flag when the identifier
            # appears nowhere else in the source text. Word-boundary
            # match: a substring count would let `time` hide inside
            # `settimeout` and exempt every short import name
            hits = len(re.findall(rf"\b{re.escape(name)}\b", self._source))
            if hits <= 1:
                self.findings.append(Finding(
                    self.path, line, "F401", f"unused import {name!r}"))


def run(sf: SourceFile) -> list[Finding]:
    """The style pass over one parsed file (line checks included)."""
    if sf.syntax_error is not None:
        e = sf.syntax_error
        return [Finding(sf.rel, e.lineno or 0, "E999",
                        f"syntax error: {e.msg}")]
    c = Checker(sf.rel, sf.tree, sf.path.name == "__init__.py", sf.source,
                in_framework=in_framework(sf.path))
    c.finish()
    for i, line in enumerate(sf.source.splitlines(), 1):
        if len(line) > MAX_LINE:
            c.findings.append(Finding(sf.rel, i, "E501",
                                      f"line too long ({len(line)} > "
                                      f"{MAX_LINE})"))
        if line != line.rstrip():
            c.findings.append(Finding(sf.rel, i, "W291",
                                      "trailing whitespace"))
        stripped_len = len(line) - len(line.lstrip())
        if "\t" in line[:stripped_len]:
            c.findings.append(Finding(sf.rel, i, "W191", "tab indentation"))
    return c.findings
