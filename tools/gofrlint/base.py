"""Shared plumbing for the gofrlint passes.

Findings, source-file discovery, the parsed-file container every pass
consumes, and `# noqa` suppression. Suppression is CENTRAL: a pass
emits every finding unconditionally and the runner filters against the
file's comment map, so `# noqa` / `# noqa: CODE` behave identically
for every rule (style, lock-discipline, TPU hot-path) instead of each
rule growing its own half-implementation.
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from pathlib import Path

MAX_LINE = 100
# lintfixtures: the analyzer's own seeded-positive test corpus
# (tests/lintfixtures/) — never part of a repo-wide run
SKIP_DIRS = {".git", "__pycache__", ".ruff_cache", "node_modules",
             ".pytest_cache", "build", "dist", "lintfixtures"}

# `# noqa` (bare: every code) or `# noqa: GL001, E501` (listed codes),
# optionally followed by prose (`# noqa: T201 — command output`).
# Case-insensitive on the marker, but it must open a `#` segment of the
# comment — `noqa` appearing in prose ("see the noqa docs") does not
# suppress anything.
_NOQA_RE = re.compile(
    r"#+\s*noqa\b(?::\s*(?P<codes>[A-Z][A-Z0-9]*(?:\s*,\s*[A-Z][A-Z0-9]*)*))?",
    re.IGNORECASE)


class Finding:
    __slots__ = ("path", "line", "code", "msg")

    def __init__(self, path: str, line: int, code: str, msg: str):
        self.path, self.line, self.code, self.msg = path, line, code, msg

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: {self.code} {self.msg}"

    def key(self) -> str:
        """Line-independent identity used by the baseline: edits above a
        finding must not churn baseline entries. Digits in the message
        are normalized away too — several messages embed line numbers
        ('redefinition ... from line N') or site counts ('at N other
        site(s)') that move with unrelated edits."""
        return f"{self.path}::{self.code}::" \
               f"{re.sub(r'[0-9]+', '#', self.msg)}"


class SourceFile:
    """One parsed source file, shared by every pass (parse once)."""

    def __init__(self, path: Path, rel: str):
        self.path = path
        self.rel = rel
        self.source = path.read_text(encoding="utf-8", errors="replace")
        self.tree: ast.AST | None = None
        self.syntax_error: SyntaxError | None = None
        try:
            self.tree = ast.parse(self.source, filename=rel)
        except SyntaxError as e:
            self.syntax_error = e
        self._comments: dict[int, str] | None = None

    # -- comments / noqa ---------------------------------------------------
    @property
    def comments(self) -> dict[int, str]:
        """lineno -> comment token text. tokenize, not a '#' scan: a '#'
        inside a string literal is not a comment and grants nothing."""
        if self._comments is None:
            self._comments = {}
            try:
                for tok in tokenize.generate_tokens(
                        io.StringIO(self.source).readline):
                    if tok.type == tokenize.COMMENT:
                        self._comments[tok.start[0]] = tok.string
            except (tokenize.TokenError, IndentationError, SyntaxError):
                pass
        return self._comments

    def noqa_codes(self, line: int) -> frozenset[str] | None:
        """None = no noqa on this line; empty frozenset = bare `# noqa`
        (suppress everything); otherwise the listed codes (uppercased)."""
        m = _NOQA_RE.search(self.comments.get(line, ""))
        if m is None:
            return None
        codes = m.group("codes")
        if codes is None:
            return frozenset()
        return frozenset(c.strip().upper() for c in codes.split(",")
                         if c.strip())

    def suppressed(self, finding: Finding) -> bool:
        if finding.code == "E999":
            # tokenize lexes comments even in files that do not PARSE,
            # but a syntax error blinds every AST pass — a file that
            # cannot be analyzed is never clean, noqa or not
            return False
        codes = self.noqa_codes(finding.line)
        if codes is None:
            return False
        return not codes or finding.code in codes


def _self_attr(node: ast.expr) -> str | None:
    """``self.X`` -> ``"X"``, else None — shared by the lock and
    hot-path passes so both agree on what counts as a self-write."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def project_parts(path: Path) -> tuple[str, ...]:
    """Path components relative to the enclosing project root (nearest
    pyproject.toml ancestor). Scope checks anchor here so a checkout at
    e.g. /home/tpu/work/repo — or one itself named gofr_tpu, the
    natural clone name — does not change what any rule applies to."""
    p = path.resolve()
    for anc in p.parents:
        if (anc / "pyproject.toml").is_file():
            return p.relative_to(anc).parts
    return p.parts


def in_framework(path: Path) -> bool:
    """Is this file part of the gofr_tpu PACKAGE?"""
    return "gofr_tpu" in project_parts(path)


def collect_files(roots: list[Path]) -> list[Path]:
    # dedupe on resolved paths: overlapping roots (`gofrlint gofr_tpu
    # gofr_tpu/tpu`) must not analyze a file twice — the duplicate
    # findings would double-count against the baseline multiset and
    # report phantom regressions
    files: list[Path] = []
    seen: set[Path] = set()

    def add(p: Path) -> None:
        rp = p.resolve()
        if rp not in seen:
            seen.add(rp)
            files.append(p)

    for r in roots:
        if r.is_file():
            add(r)
            continue
        for p in sorted(r.rglob("*.py")):
            if any(part in SKIP_DIRS for part in p.parts):
                continue
            if p.name.endswith("_pb2.py"):  # protoc-generated
                continue
            add(p)
    return files
