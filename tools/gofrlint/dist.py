"""Distributed-safety pass: GL301-GL304 over the framework's
concurrency and wire idioms.

The PR 12-19 surface (pd/ wire protocol, gateway relay, tenancy,
durable resume) multiplied threads, sockets and failure paths — and
every serious bug in it was caught by review, not tooling: socket
errors surfacing as raw 500s instead of typed sheds, an accept loop
that never woke on close, flush threads outliving shutdown. This pass
encodes those review findings as rules:

GL301 — blocking call under a held lock. Reuses the lock pass's
guard-region inference AND its call-graph lock-inheritance fixpoint
(a private method called only under L is analyzed as holding L), so
`self._send_locked()` bodies and try-acquire early-outs inherit the
same exemptions GL001 grants. Blocking shapes: socket
send/recv/connect/accept, `queue.get/put` with no timeout,
`Thread.join`, `time.sleep`, `Event.wait`, `jax.device_get` /
`block_until_ready`, and subprocess waits. Two idiom exemptions keep
the rule honest: a lock whose NAME says it serializes device work
(`*device*`/`*dispatch*`) may be held across device syncs — that is
its job — and a lock named for the write side of a connection
(`*send*`/`*write*`/`*tx*`/`*conn*`/`*sock*`/`*out*`) may be held
across socket sends, the serialize-the-writers idiom. Waiting on a
Condition releases only ITS lock: `cond.wait()` while holding a
second lock is still flagged.

GL302 — thread-lifecycle leak. A non-daemon `threading.Thread`
started from a class must be `join()`ed from that class's teardown
path — `close()`/`shutdown()`/`stop()`/`__exit__`/... or a method
they call — and a started thread dropped on the floor (neither
stored, joined, nor daemonized) is flagged at the construction site.
`daemon=True` is the declared justification (the thread must then
survive being abandoned); the join scan follows `self._t.join()`,
`for t in self._threads: t.join()`, and local aliases.

GL303 — unmapped failure path, the raw-500 bug class. (a) A
request-path function (handle/serve/relay/stream/recv/... naming) in
framework code raising a BUILTIN exception — peer loss and bad input
must surface as typed `errors.py` classes so the wire maps them to
429/502/503/504 instead of a raw 500. (b) An `except` arm catching
`OSError`/`EOFError`/socket errors that neither re-raises, converts
to a typed `*Error` class, exits the loop/function, nor routes to a
reject/close path — i.e. it swallows transport loss and falls
through as if the peer were still there. Teardown/cold functions
(`close`, `shutdown`, `warmup`, `__init__`, ...) are exempt from (b):
best-effort cleanup legitimately ignores socket errors.

GL304 — metric discipline. Emitting a literal metric name that no
`new_counter`/`new_histogram`/`new_gauge`/`new_updown_counter` call
ever registers (the emit silently no-ops or explodes depending on
backend); a NON-literal metric name (unbounded series cardinality) —
except the forwarding-helper idiom where the name is a parameter of
the enclosing function, and locals provably bound only to string
literals; and label-key sets inconsistent across the emit sites of
one counter/histogram (`exemplar`/`value` are API kwargs, not
labels; `**labels` forwarding sites are skipped).

GL301/GL302 consume the lock pass's per-class state after its
fixpoint, so this pass's finish() must run after LockPass.finish().
"""

from __future__ import annotations

import ast
import re

from .base import Finding, SourceFile, _self_attr, in_framework
from .hotpath import _callee_last, _callee_root
from .locks import LockPass, _Class, _Method, _ctor_name

# -- GL301 tables -------------------------------------------------------------
_QUEUE_CTORS = {"Queue", "LifoQueue", "PriorityQueue", "SimpleQueue",
                "JoinableQueue"}
_SOCK_METHODS = {"sendall", "recv", "recv_into", "recvfrom", "accept",
                 "connect"}
_SOCK_HINT = re.compile(r"sock|conn|peer|listener", re.I)
_QUEUE_HINT = re.compile(r"(^|_)(q|queue|inbox|outbox|jobs|backlog|fifo)"
                         r"\d*$|queue", re.I)
_THREAD_HINT = re.compile(r"thread|worker|reaper|poller|waiter", re.I)
_PROC_HINT = re.compile(r"proc|popen|child", re.I)
# a lock that EXISTS to serialize device dispatch may be held across
# device syncs; a write-side connection lock may be held across sends
_DEVICE_LOCK = re.compile(r"device|dispatch", re.I)
_IO_LOCK = re.compile(r"send|write|tx|out|conn|sock|io|wlock", re.I)

# -- GL302 tables -------------------------------------------------------------
_TEARDOWN_RE = re.compile(
    r"^(close|shutdown|stop|terminate|teardown|drain|uninstall"
    r"|disconnect|join|finish|release|cancel|wait_closed|aclose"
    r"|__exit__|__del__)($|_)")

# -- GL303 tables -------------------------------------------------------------
_BUILTIN_EXC = {"Exception", "BaseException", "RuntimeError", "ValueError",
                "TypeError", "KeyError", "IndexError", "LookupError",
                "OSError", "IOError", "EOFError", "ConnectionError",
                "ConnectionResetError", "ConnectionAbortedError",
                "BrokenPipeError", "TimeoutError", "ArithmeticError"}
_WIRE_EXC = {"OSError", "IOError", "EOFError", "ConnectionError",
             "ConnectionResetError", "ConnectionAbortedError",
             "BrokenPipeError", "TimeoutError", "InterruptedError",
             "herror", "gaierror", "timeout", "error"}
# "timeout"/"error"/"herror"/"gaierror" only count when socket-qualified
_WIRE_EXC_BARE = _WIRE_EXC - {"timeout", "error", "herror", "gaierror"}
_REQ_PATH_RE = re.compile(
    r"^(handle|serve|do|call|request|invoke|dispatch|relay|forward"
    r"|stream|recv|send|read|write|submit|ingest|fetch|route|generate"
    r"|predict|reply|respond|pick|push|pull|poll|accept)($|_)")
# matched against the name with leading underscores stripped, so
# dunders appear as their cores (init/del/exit)
_COLD_RE = re.compile(
    r"^(close|shutdown|stop|drain|uninstall|terminate|teardown|cleanup"
    r"|reset|warmup|health|probe|poke|cancel|abort|init|del|exit)($|_)")
# a handler body call whose name routes the failure somewhere typed:
# reject/fail/abort/shed paths, or the wire's error_to_wire converter
_ROUTE_RE = re.compile(
    r"^_{0,2}(reject|fail|abort|shed|drop|error_to_wire|on_error"
    r"|record_failure|mark_down|mark_dead|quarantine|(re)?connect"
    r"|retry)($|_)")
# OSError around FILE I/O is not transport loss: a handler whose try
# body opens/stats paths is doing config/procfs reads, not wire reads
_FILE_IO = {"open", "read_text", "read_bytes", "write_text", "stat",
            "unlink", "mkdir", "makedirs", "listdir", "glob", "remove",
            "rename", "replace", "exists", "getmtime", "isfile",
            "isdir", "CDLL"}
_TYPED_EXC_RE = re.compile(r"(Error|Exception|Exhausted|Lost|Expired"
                           r"|Timeout|Refused|Open)$")

# -- GL304 tables -------------------------------------------------------------
_REG_VERBS = {"new_counter", "new_histogram", "new_gauge",
              "new_updown_counter"}
_EMIT_VERBS = {"increment_counter", "record_histogram", "set_gauge",
               "delta_updown_counter"}
_CONSISTENCY_VERBS = {"increment_counter", "record_histogram"}
_NON_LABEL_KWARGS = {"exemplar", "value", "delta"}


def _recv_name(expr: ast.expr) -> str | None:
    """Best-effort NAME of a call receiver, through subscripts:
    ``self._sock`` -> ``_sock``, ``conns[i]`` -> ``conns``."""
    while isinstance(expr, ast.Subscript):
        expr = expr.value
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    return None


def _recv_self_attr(expr: ast.expr) -> str | None:
    while isinstance(expr, ast.Subscript):
        expr = expr.value
    return _self_attr(expr)


def _const_false(node: ast.expr | None) -> bool:
    return isinstance(node, ast.Constant) and node.value is False


def _has_real_timeout(call: ast.Call) -> bool:
    """A ``timeout=`` kwarg that is not literally None bounds the wait."""
    for kw in call.keywords:
        if kw.arg == "timeout":
            return not (isinstance(kw.value, ast.Constant)
                        and kw.value.value is None)
    return False


class _ClassInfo:
    """Per-class facts GL301/GL302 need beyond what LockPass keeps:
    which self attrs hold which constructor type, and which hold
    threads (with their daemon-ness and construction site)."""

    def __init__(self, cls: _Class):
        self.attr_ctor: dict[str, str] = {}
        # queue attrs constructed with a nonzero maxsize: put() BLOCKS
        # on these when full; put() on an unbounded queue never does
        self.bounded_queues: set[str] = set()
        # attr -> [ctor lineno, daemon, started]; covers both
        # `self._t = Thread(...)` and `self._ts.append(Thread(...))`
        self.threads: dict[str, list] = {}
        # (lineno, method) of started non-daemon threads with no owner
        self.dropped: list[tuple[int, str]] = []
        for meth in cls.methods.values():
            self._scan(meth)

    def _scan(self, meth: _Method) -> None:
        # (lineno, daemon, started, escaped) per local thread name
        local: dict[str, list] = {}
        for node in ast.walk(meth.node):
            if isinstance(node, ast.Assign):
                ctor = _ctor_name(node.value)
                for t in node.targets:
                    attr = _self_attr(t)
                    if attr is not None and ctor is not None:
                        self.attr_ctor.setdefault(attr, ctor)
                        if ctor in _QUEUE_CTORS and \
                                _queue_bounded(node.value):
                            self.bounded_queues.add(attr)
                        if ctor == "Thread":
                            self.threads.setdefault(attr, [
                                node.value.lineno,
                                _thread_daemon(node.value), False])
                    if attr is not None and isinstance(node.value,
                                                       ast.Name) and \
                            node.value.id in local:
                        # `self._t = t` adopts the local thread
                        rec = local[node.value.id]
                        rec[3] = True
                        self.threads.setdefault(attr, rec[:3])
                    # `self._t.daemon = True` / `t.daemon = True`
                    if isinstance(t, ast.Attribute) and \
                            t.attr == "daemon" and \
                            isinstance(node.value, ast.Constant) and \
                            node.value.value is True:
                        owner = _recv_self_attr(t.value)
                        if owner in self.threads:
                            self.threads[owner][1] = True
                        elif isinstance(t.value, ast.Name) and \
                                t.value.id in local:
                            local[t.value.id][1] = True
                    if isinstance(t, ast.Name) and ctor == "Thread":
                        local[t.id] = [node.value.lineno,
                                       _thread_daemon(node.value),
                                       False, False]
            if not isinstance(node, ast.Call) or \
                    not isinstance(node.func, ast.Attribute):
                continue
            recv, verb = node.func.value, node.func.attr
            if verb == "append" and node.args:
                attr = _recv_self_attr(recv)
                arg = node.args[0]
                if attr is not None:
                    if _ctor_name(arg) == "Thread":
                        self.threads.setdefault(attr, [
                            arg.lineno, _thread_daemon(arg), False])
                    elif isinstance(arg, ast.Name) and arg.id in local:
                        rec = local[arg.id]
                        rec[3] = True
                        self.threads.setdefault(attr, rec[:3])
            elif verb == "start":
                attr = _recv_self_attr(recv)
                if attr in self.threads:
                    self.threads[attr][2] = True
                elif isinstance(recv, ast.Name) and recv.id in local:
                    local[recv.id][2] = True
                elif _ctor_name(recv) == "Thread":
                    # Thread(...).start(): inline fire-and-forget
                    local[f"<inline:{recv.lineno}>"] = [
                        recv.lineno, _thread_daemon(recv), True, False]
            elif verb == "join":
                if isinstance(recv, ast.Name) and recv.id in local:
                    local[recv.id][3] = True  # joined locally: owned
        # a started, non-daemon local thread that neither escaped to an
        # attribute nor was joined in-method is dropped on the floor
        for rec in local.values():
            lineno, daemon, started, owned = rec
            if started and not daemon and not owned:
                self.dropped.append((lineno, meth.name))


def _queue_bounded(call: ast.expr) -> bool:
    """Queue(N)/Queue(maxsize=N) with N != 0 (or non-constant) blocks
    producers when full; a bare Queue() never blocks put()."""
    if not isinstance(call, ast.Call):
        return False
    if _ctor_name(call) == "SimpleQueue":
        return False
    cap = call.args[0] if call.args else None
    for kw in call.keywords:
        if kw.arg == "maxsize":
            cap = kw.value
    if cap is None:
        return False
    return not (isinstance(cap, ast.Constant) and not cap.value)


def _thread_daemon(call: ast.expr) -> bool:
    if not isinstance(call, ast.Call):
        return False
    for kw in call.keywords:
        if kw.arg == "daemon":
            return isinstance(kw.value, ast.Constant) and \
                kw.value.value is True
    return False


class _Emit:
    __slots__ = ("rel", "line", "verb", "names", "literal", "labels",
                 "starstar")

    def __init__(self, rel, line, verb, names, literal, labels, starstar):
        self.rel, self.line, self.verb = rel, line, verb
        self.names, self.literal = names, literal
        self.labels, self.starstar = labels, starstar


class DistPass:
    """Whole-run distributed-safety analysis. feed() per file;
    finish(lock_pass) AFTER LockPass.finish() — GL301/GL302 read the
    post-fixpoint per-class state."""

    def __init__(self):
        self.findings: list[Finding] = []
        self._registered: set[str] = set()
        self._emits: list[_Emit] = []

    # -- per-file ----------------------------------------------------------
    def feed(self, sf: SourceFile) -> None:
        if sf.tree is None:
            return
        self._collect_registrations(sf)
        if not in_framework(sf.path):
            return
        self._feed_gl303(sf)
        if not sf.rel.endswith("gofr_tpu/metrics.py"):
            # the Manager's own emit methods forward by construction
            self._collect_emits(sf)

    # -- GL303 -------------------------------------------------------------
    def _feed_gl303(self, sf: SourceFile) -> None:
        def visit(node: ast.AST, fname: str) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    visit(child, child.name)
                    continue
                if isinstance(child, ast.ClassDef):
                    visit(child, fname)
                    continue
                if isinstance(child, ast.Raise):
                    self._check_raise(sf, child, fname)
                elif isinstance(child, ast.Try):
                    for h in child.handlers:
                        self._check_handler(sf, child, h, fname)
                visit(child, fname)

        visit(sf.tree, "<module>")

    def _check_raise(self, sf: SourceFile, node: ast.Raise,
                     fname: str) -> None:
        core = fname.lstrip("_")
        if not (_REQ_PATH_RE.match(core) or fname == "__call__"):
            return
        exc = node.exc
        if isinstance(exc, ast.Call):
            exc = exc.func
        name = _callee_last(exc) if exc is not None else None
        if name in _BUILTIN_EXC:
            self.findings.append(Finding(
                sf.rel, node.lineno, "GL303",
                f"request-path {fname}() raises builtin {name} — raise "
                f"a typed errors.py class so the wire maps it (peer "
                f"sees 4xx/5xx with a reason, not a raw 500)"))

    def _check_handler(self, sf: SourceFile, try_node: ast.Try,
                       h: ast.ExceptHandler, fname: str) -> None:
        core = fname.lstrip("_")
        if _COLD_RE.match(core) or fname == "<module>":
            return
        if not self._catches_wire_errors(h.type):
            return
        if self._handler_routes(h.body):
            return
        if h.name is not None and any(
                isinstance(n, ast.Name) and n.id == h.name
                for n in ast.walk(ast.Module(body=list(h.body),
                                             type_ignores=[]))):
            return  # the body USES the exception: converting/recording
        if self._teardown_in(try_node.finalbody):
            return  # `finally: self.close()` — the failure ENDS the
            # connection; falling out of the handler is not success
        for node in ast.walk(ast.Module(body=list(try_node.body),
                                        type_ignores=[])):
            if isinstance(node, ast.Call) and \
                    _callee_last(node.func) in _FILE_IO:
                return  # file I/O, not wire: missing files are normal
        self.findings.append(Finding(
            sf.rel, h.lineno, "GL303",
            f"handler in {fname}() swallows a transport error "
            f"(OSError family) without re-raising, converting to a "
            f"typed errors.py class, or exiting the request — peer "
            f"loss falls through as success"))

    def _catches_wire_errors(self, t: ast.expr | None) -> bool:
        if t is None:
            return False  # bare except: E722's finding
        types = t.elts if isinstance(t, ast.Tuple) else [t]
        for e in types:
            name = _callee_last(e)
            if name in _WIRE_EXC_BARE:
                return True
            if name in ("timeout", "error", "herror", "gaierror") and \
                    _callee_root(e) == "socket":
                return True
        return False

    def _teardown_in(self, body: list[ast.stmt]) -> bool:
        for node in ast.walk(ast.Module(body=list(body), type_ignores=[])):
            if isinstance(node, ast.Call):
                name = _callee_last(node.func)
                if name is not None and (
                        _TEARDOWN_RE.match(name.lstrip("_"))
                        or _ROUTE_RE.match(name)):
                    return True
        return False

    def _handler_routes(self, body: list[ast.stmt]) -> bool:
        for node in ast.walk(ast.Module(body=list(body), type_ignores=[])):
            if isinstance(node, (ast.Raise, ast.Return, ast.Break,
                                 ast.Continue)):
                return True
            if isinstance(node, ast.Call):
                name = _callee_last(node.func)
                if name is None:
                    continue
                if _ROUTE_RE.match(name):
                    return True
                if name not in _BUILTIN_EXC and _TYPED_EXC_RE.search(name):
                    return True  # constructs a typed error class
        return False

    # -- GL304 -------------------------------------------------------------
    def _collect_registrations(self, sf: SourceFile) -> None:
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _REG_VERBS and node.args and \
                    isinstance(node.args[0], ast.Constant) and \
                    isinstance(node.args[0].value, str):
                self._registered.add(node.args[0].value)

    def _collect_emits(self, sf: SourceFile) -> None:
        # module-level UPPER_CASE = "literal" metric-name constants
        # (hbm.py's GAUGE/BUDGET_GAUGE idiom) resolve as literals
        consts: dict[str, str] = {}
        for stmt in sf.tree.body:
            if isinstance(stmt, ast.Assign) and \
                    len(stmt.targets) == 1 and \
                    isinstance(stmt.targets[0], ast.Name) and \
                    stmt.targets[0].id.isupper() and \
                    isinstance(stmt.value, ast.Constant) and \
                    isinstance(stmt.value.value, str):
                consts[stmt.targets[0].id] = stmt.value.value

        def visit(node: ast.AST, fn) -> None:
            for child in ast.iter_child_nodes(node):
                nxt = fn
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef,
                                      ast.Lambda)):
                    nxt = child
                if isinstance(child, ast.Call) and \
                        isinstance(child.func, ast.Attribute) and \
                        child.func.attr in _EMIT_VERBS:
                    self._record_emit(sf, child, fn, consts)
                visit(child, nxt)

        visit(sf.tree, None)

    def _record_emit(self, sf: SourceFile, call: ast.Call, fn,
                     consts: dict[str, str]) -> None:
        verb = call.func.attr
        name_expr = call.args[0] if call.args else None
        if name_expr is None:
            for kw in call.keywords:
                if kw.arg == "name":
                    name_expr = kw.value
        labels = frozenset(kw.arg for kw in call.keywords
                           if kw.arg is not None
                           and kw.arg not in _NON_LABEL_KWARGS)
        starstar = any(kw.arg is None for kw in call.keywords)
        if isinstance(name_expr, ast.Constant) and \
                isinstance(name_expr.value, str):
            self._emits.append(_Emit(sf.rel, call.lineno, verb,
                                     {name_expr.value}, True, labels,
                                     starstar))
            return
        if isinstance(name_expr, ast.Name):
            if fn is not None and name_expr.id in _param_names(fn):
                return  # forwarding helper: callers own the name
            if name_expr.id in consts:
                self._emits.append(_Emit(sf.rel, call.lineno, verb,
                                         {consts[name_expr.id]}, True,
                                         labels, starstar))
                return
            names = _literal_bindings(fn, name_expr.id) \
                if fn is not None else None
            if names:
                self._emits.append(_Emit(sf.rel, call.lineno, verb,
                                         names, True, labels, starstar))
                return
        self.findings.append(Finding(
            sf.rel, call.lineno, "GL304",
            f"{verb}() with a non-literal metric name — dynamic names "
            f"are unbounded series cardinality; use a literal name "
            f"with labels, or forward through a helper whose name is "
            f"a parameter"))

    # -- whole-run ---------------------------------------------------------
    def finish(self, lock_pass: LockPass) -> list[Finding]:
        for cls in lock_pass.classes:
            rel = lock_pass._class_file[id(cls)]
            info = _ClassInfo(cls)
            self._check_gl301(cls, info, rel)
            self._check_gl302(cls, info, rel)
        self._check_gl304()
        self.findings.sort(key=lambda f: (f.path, f.line, f.code))
        return self.findings

    # -- GL301 -------------------------------------------------------------
    def _check_gl301(self, cls: _Class, info: _ClassInfo,
                     rel: str) -> None:
        seen: set[tuple[int, str]] = set()
        for m in cls.methods.values():
            if m.exempt:
                continue
            for call, held in m.calls:
                eff = frozenset(held | m.inherited)
                if not eff:
                    continue
                desc = self._blocking(cls, info, call, eff)
                if desc is None:
                    continue
                blocking, under = desc
                key = (call.lineno, blocking)
                if key in seen:
                    continue
                seen.add(key)
                self.findings.append(Finding(
                    rel, call.lineno, "GL301",
                    f"{blocking} while holding "
                    f"{'/'.join(sorted(under))} in {cls.name}.{m.name} "
                    f"— every other waiter on the lock stalls behind "
                    f"this call; move it outside the region or use a "
                    f"timeout/nowait form"))

    def _blocking(self, cls: _Class, info: _ClassInfo, call: ast.Call,
                  eff: frozenset[str]):
        f = call.func
        if isinstance(f, ast.Name):
            if f.id == "sleep":
                return ("time.sleep()", eff)
            if f.id in ("device_get", "block_until_ready"):
                rem = {lk for lk in eff if not _DEVICE_LOCK.search(lk)}
                return (f"device sync {f.id}()", rem) if rem else None
            if f.id == "create_connection":
                rem = {lk for lk in eff if not _IO_LOCK.search(lk)}
                return ("socket connect (create_connection)", rem) \
                    if rem else None
            return None
        if not isinstance(f, ast.Attribute):
            return None
        verb, recv = f.attr, f.value
        root = _callee_root(f)
        a = _recv_self_attr(recv)
        rname = _recv_name(recv) or ""
        if a is not None and a in cls.locks:
            # lock/condition receivers: .acquire is GL002's business;
            # cond.wait RELEASES its own lock — but no other held one
            if verb in ("wait", "wait_for"):
                rem = eff - {cls.locks[a]}
                return (f"Condition self.{a}.{verb}() (releases only "
                        f"its own lock)", rem) if rem else None
            return None
        ctor = info.attr_ctor.get(a) if a is not None else None
        if verb == "sleep" and root == "time":
            return ("time.sleep()", eff)
        if verb in ("device_get", "block_until_ready"):
            rem = {lk for lk in eff if not _DEVICE_LOCK.search(lk)}
            return (f"device sync {verb}()", rem) if rem else None
        if (verb in _SOCK_METHODS or verb == "send") and \
                (_SOCK_HINT.search(rname) or ctor == "socket"):
            # the NAME must say socket: bare .accept()/.recv() also
            # live on prefix indexes, kv caches, channels...
            rem = {lk for lk in eff if not _IO_LOCK.search(lk)}
            return (f"socket {rname or '<sock>'}.{verb}()", rem) \
                if rem else None
        if verb == "create_connection" and root == "socket":
            rem = {lk for lk in eff if not _IO_LOCK.search(lk)}
            return ("socket connect (create_connection)", rem) \
                if rem else None
        if verb in ("get", "put") and \
                (ctor in _QUEUE_CTORS or _QUEUE_HINT.search(rname)):
            if ctor is not None and ctor not in _QUEUE_CTORS:
                return None  # known non-queue attr (e.g. a dict)
            if verb == "put" and a not in info.bounded_queues:
                # put() only blocks when the queue has a maxsize; an
                # unbounded (or unknowable) queue's put never waits
                return None
            if _has_real_timeout(call):
                return None
            if any(kw.arg == "block" and _const_false(kw.value)
                   for kw in call.keywords):
                return None
            pos = 0 if verb == "get" else 1
            if len(call.args) > pos and _const_false(call.args[pos]):
                return None
            return (f"queue {rname}.{verb}() with no timeout", eff)
        if verb == "join":
            if ctor == "Thread" or a in info.threads or \
                    (ctor is None and _THREAD_HINT.search(rname)):
                return (f"Thread {rname}.join()", eff)
            if ctor in _QUEUE_CTORS:
                return (f"queue {rname}.join()", eff)
            return None
        if verb == "wait":
            if ctor == "Event":
                return (f"Event self.{a}.wait()", eff)
            if ctor is None and _PROC_HINT.search(rname):
                return (f"process {rname}.wait()", eff)
            return None
        if verb == "communicate":
            return (f"process {rname}.communicate()", eff)
        if verb in ("run", "check_call", "check_output", "call") and \
                root == "subprocess":
            return (f"subprocess.{verb}()", eff)
        return None

    # -- GL302 -------------------------------------------------------------
    def _check_gl302(self, cls: _Class, info: _ClassInfo,
                     rel: str) -> None:
        for lineno, mname in getattr(info, "dropped", []):
            self.findings.append(Finding(
                rel, lineno, "GL302",
                f"non-daemon thread started in {cls.name}.{mname} is "
                f"neither stored, joined, nor daemon=True — it "
                f"outlives the request with no owner and no stop path"))
        leaked = {attr: rec for attr, rec in info.threads.items()
                  if rec[2] and not rec[1]}  # started, not daemon
        if not leaked:
            return
        joined = self._teardown_joined(cls)
        for attr, (lineno, _, _) in sorted(leaked.items()):
            if attr in joined:
                continue
            self.findings.append(Finding(
                rel, lineno, "GL302",
                f"thread self.{attr} started in {cls.name} is never "
                f"join()ed from a teardown path (close/shutdown/stop/"
                f"__exit__ or a method they call) — it outlives the "
                f"owner; join it on close or declare daemon=True with "
                f"a wake mechanism"))

    def _teardown_joined(self, cls: _Class) -> set[str]:
        """Self attrs join()ed from any method reachable from a
        teardown-named method via self-calls."""
        reach = {n for n in cls.methods if _TEARDOWN_RE.match(n)}
        frontier = list(reach)
        while frontier:
            m = cls.methods.get(frontier.pop())
            if m is None:
                continue
            for callee, _, _ in m.self_calls:
                if callee in cls.methods and callee not in reach:
                    reach.add(callee)
                    frontier.append(callee)
        joined: set[str] = set()
        for n in reach:
            joined |= self._joined_attrs(cls.methods[n].node)
        return joined

    def _joined_attrs(self, node: ast.AST) -> set[str]:
        out: set[str] = set()
        # local-name -> self attrs it may refer to (for-loop targets
        # over self._threads, `t = self._thread` aliases, .pop() pulls)
        aliases: dict[str, set[str]] = {}
        for n in ast.walk(node):
            if isinstance(n, (ast.For, ast.AsyncFor)) and \
                    isinstance(n.target, ast.Name):
                attrs = {a for sub in ast.walk(n.iter)
                         if (a := _self_attr(sub)) is not None}
                if attrs:
                    aliases.setdefault(n.target.id, set()).update(attrs)
            if isinstance(n, ast.Assign) and len(n.targets) == 1 and \
                    isinstance(n.targets[0], ast.Name):
                attrs = {a for sub in ast.walk(n.value)
                         if (a := _self_attr(sub)) is not None}
                if attrs:
                    aliases.setdefault(n.targets[0].id,
                                       set()).update(attrs)
        for n in ast.walk(node):
            if isinstance(n, ast.Call) and \
                    isinstance(n.func, ast.Attribute) and \
                    n.func.attr == "join":
                recv = n.func.value
                a = _recv_self_attr(recv)
                if a is not None:
                    out.add(a)
                elif isinstance(recv, ast.Name):
                    out |= aliases.get(recv.id, set())
        return out

    # -- GL304 finish ------------------------------------------------------
    def _check_gl304(self) -> None:
        # "unregistered" is only decidable when the run INCLUDES a
        # registration surface (metrics.py): a single-module run sees
        # no new_* calls at all, and flagging every emit there would
        # be noise, not analysis
        for e in self._emits if self._registered else ():
            for name in sorted(e.names - self._registered):
                self.findings.append(Finding(
                    e.rel, e.line, "GL304",
                    f"metric '{name}' is emitted here but never "
                    f"registered (no new_counter/new_histogram/"
                    f"new_gauge/new_updown_counter anywhere in the "
                    f"run) — register it in "
                    f"metrics.register_framework_metrics or delete "
                    f"the emit"))
        by_name: dict[str, list[_Emit]] = {}
        for e in self._emits:
            if e.verb in _CONSISTENCY_VERBS and not e.starstar and \
                    len(e.names) == 1:
                by_name.setdefault(next(iter(e.names)), []).append(e)
        for name, sites in sorted(by_name.items()):
            variants = {}
            for e in sites:
                variants.setdefault(e.labels, []).append(e)
            if len(variants) < 2:
                continue
            # majority label set wins; every divergent site is flagged
            best = sorted(variants.items(),
                          key=lambda kv: (-len(kv[1]),
                                          sorted(kv[0])))[0][0]
            n_best = len(variants[best])
            for labels, es in sorted(variants.items(),
                                     key=lambda kv: sorted(kv[0])):
                if labels == best:
                    continue
                for e in es:
                    self.findings.append(Finding(
                        e.rel, e.line, "GL304",
                        f"metric '{name}' emitted with label keys "
                        f"{{{', '.join(sorted(labels)) or ''}}} here "
                        f"but {{{', '.join(sorted(best))}}} at "
                        f"{n_best} other site(s) — per-metric label "
                        f"keys must be one consistent set"))


def _param_names(fn) -> set[str]:
    a = fn.args
    names = {p.arg for p in a.posonlyargs + a.args + a.kwonlyargs}
    if a.vararg is not None:
        names.add(a.vararg.arg)
    if a.kwarg is not None:
        names.add(a.kwarg.arg)
    return names


def _literal_bindings(fn, name: str) -> set[str] | None:
    """The literal strings ``name`` may hold inside ``fn``, or None if
    any binding is unresolvable (a computed name)."""
    out: set[str] = set()
    found = False
    for node in ast.walk(fn):
        if not isinstance(node, ast.Assign):
            continue
        if not any(isinstance(t, ast.Name) and t.id == name
                   for t in node.targets):
            continue
        found = True
        vals = [node.value]
        if isinstance(node.value, ast.IfExp):
            vals = [node.value.body, node.value.orelse]
        for v in vals:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                out.add(v.value)
            else:
                return None
    return out if found else None
