"""Lock-discipline pass: GL001 (unguarded writes) + GL002 (order cycles).

GL001 — per-class guard inference. For every class the pass records
which ``threading.Lock/RLock/Condition`` attributes exist (a Condition
constructed over ``self._lock`` is an ALIAS: holding it is holding the
lock) and which locks are held, lexically, at every write to a ``self``
attribute. Holding is tracked through:

  - ``with self._lock:`` regions (any nesting, multiple items);
  - the bare ``self._lock.acquire()`` statement (held until a
    statement-level ``release()`` or the end of the suite; a
    ``try/finally`` whose finally releases covers the classic pattern);
  - the ``if not self._lock.acquire(False): ...return`` idiom (held
    after the early-out branch);
  - interprocedural inheritance: a private method called ONLY from
    sites that hold L is analyzed as holding L (fixpoint over the
    in-class call graph; methods whose reference escapes — stored,
    passed to partial(), exported — inherit nothing);
  - an explicit annotation ``# gl: holds self._lock`` on the ``def``
    line, for callbacks invoked under a lock the analyzer cannot see
    through (e.g. a closure handed to another thread's executor);
  - methods named ``*_locked`` are the caller-holds-the-lock
    convention: their bodies are exempt from GL001 entirely.

An attribute written at least once under a lock and at least once
under none — with the guarded sites in the majority — is flagged at
each naked site. Writes under DIFFERENT locks with no common guard are
flagged as inconsistent. ``__init__``/``__del__`` writes are exempt
(the object is not shared yet/anymore), as are attributes that are
themselves synchronization or thread-safe-by-construction objects
(locks, Events, queue.Queue).

GL002 — the cross-module lock-order graph. Acquiring B while holding A
adds the edge A -> B, where nodes are (class, attribute) — the lock's
DECLARATION, so order is checked per lock class like lockdep, across
every module in the run. One level of cross-object calls is followed:
``x.m()`` under a held lock adds edges to the locks ``m`` may acquire,
when ``m`` resolves to at most two lock-acquiring classes. Any cycle in
the final graph is a potential deadlock and is reported once, on its
lexically first edge.
"""

from __future__ import annotations

import ast
import re

from .base import Finding, SourceFile, _self_attr, in_framework

_LOCK_CTORS = {"Lock", "RLock"}
_COND_CTORS = {"Condition"}
# Thread-safe-by-construction (or synchronization primitives): writes
# to these attrs are exempt from GL001 — mutating an Event or a
# queue.Queue needs no caller-side lock.
_EXEMPT_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
                 "BoundedSemaphore", "Event", "Barrier", "Queue",
                 "SimpleQueue", "LifoQueue", "PriorityQueue", "local"}
_MUTATORS = {"append", "appendleft", "extend", "extendleft", "insert",
             "pop", "popleft", "remove", "clear", "update", "add",
             "discard", "setdefault", "popitem"}
_GL_HOLDS_RE = re.compile(r"#\s*gl:\s*holds\s+(?P<locks>[\w.,\s]+)")


def _ctor_name(node: ast.expr) -> str | None:
    """Last segment of a constructor callee: threading.Lock -> Lock."""
    if not isinstance(node, ast.Call):
        return None
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


class _Method:
    def __init__(self, name: str, node: ast.AST):
        self.name = name
        self.node = node
        # (attr, lineno, lexical-held frozenset)
        self.writes: list[tuple[str, int, frozenset[str]]] = []
        # (callee-method-name, lexical-held, lineno)
        self.self_calls: list[tuple[str, frozenset[str], int]] = []
        # (method-name, lexical-held, lineno) on a non-self receiver
        self.obj_calls: list[tuple[str, frozenset[str], int]] = []
        # (lock, held-before frozenset, lineno)
        self.acquires: list[tuple[str, frozenset[str], int]] = []
        # EVERY call expression with the lexical held set at the site —
        # consumed by the dist pass (GL301 blocking-under-lock), which
        # adds the inherited/annotated locks after the fixpoint
        self.calls: list[tuple[ast.Call, frozenset[str]]] = []
        self.annotated: frozenset[str] = frozenset()
        self.inherited: frozenset[str] = frozenset()
        self.construction_only = False  # called only from __init__/__del__

    @property
    def exempt(self) -> bool:
        return self.name in ("__init__", "__del__") or \
            self.name.endswith("_locked") or self.construction_only


class _Class:
    def __init__(self, module: str, name: str, bases: list[str]):
        self.module = module
        self.name = name
        self.bases = bases
        self.locks: dict[str, str] = {}    # attr -> canonical attr
        self.exempt_attrs: set[str] = set()
        self.methods: dict[str, _Method] = {}
        self.escaped: set[str] = set()     # method names whose ref escapes

    def node_id(self, lock_attr: str) -> str:
        if lock_attr.startswith("<module"):
            # a module-level lock is ONE lock shared by every class in
            # the module: per-class prefixing would split it into
            # distinct graph nodes and hide real cross-class cycles
            return lock_attr
        return f"{self.name}.{self.locks.get(lock_attr, lock_attr)}"


class _MethodWalker:
    """Statement-ordered walk of one method body, tracking held locks."""

    def __init__(self, cls: _Class, meth: _Method, module_locks: set[str],
                 sf: SourceFile):
        self.cls = cls
        self.meth = meth
        self.module_locks = module_locks
        self.sf = sf

    def _lock_of(self, expr: ast.expr) -> str | None:
        """Canonical lock name for an acquired context expr, or None."""
        a = _self_attr(expr)
        if a is not None and a in self.cls.locks:
            return self.cls.locks[a]
        if isinstance(expr, ast.Name) and expr.id in self.module_locks:
            # qualified by the declaring module: same-named locks in
            # different files must not collapse into one node
            return f"<module {self.cls.module}>.{expr.id}"
        return None

    def _acquire_call(self, call: ast.expr, want: str) -> str | None:
        """The canonical lock when ``call`` is ``<lock>.acquire()`` /
        ``.release()`` (want selects which)."""
        if isinstance(call, ast.Call) and \
                isinstance(call.func, ast.Attribute) and \
                call.func.attr == want:
            return self._lock_of(call.func.value)
        return None

    def walk(self, body: list[ast.stmt], held: frozenset[str]) -> None:
        """Walk ``body`` in order; ``held`` is the entry lock set.
        Acquire/release statements mutate the running set."""
        for stmt in body:
            held = self._stmt(stmt, held)

    def _stmt(self, stmt: ast.stmt, held: frozenset[str]) -> frozenset[str]:
        if isinstance(stmt, ast.With):
            inner = set(held)
            for item in stmt.items:
                lk = self._lock_of(item.context_expr)
                if lk is not None:
                    self.meth.acquires.append(
                        (lk, frozenset(inner), stmt.lineno))
                    inner.add(lk)
                else:
                    self._expr(item.context_expr, held)
            self.walk(stmt.body, frozenset(inner))
            return held
        if isinstance(stmt, ast.Expr):
            lk = self._acquire_call(stmt.value, "acquire")
            if lk is not None:
                self.meth.acquires.append((lk, held, stmt.lineno))
                return held | {lk}
            lk = self._acquire_call(stmt.value, "release")
            if lk is not None:
                return held - {lk}
            self._expr(stmt.value, held)
            return held
        if isinstance(stmt, ast.If):
            # `if not X.acquire(...): <terminating body>` — the fall-
            # through path holds X
            test = stmt.test
            acquired = None
            if isinstance(test, ast.UnaryOp) and \
                    isinstance(test.op, ast.Not):
                acquired = self._acquire_call(test.operand, "acquire")
            terminates = bool(stmt.body) and isinstance(
                stmt.body[-1], (ast.Return, ast.Raise, ast.Continue,
                                ast.Break))
            if acquired is not None and terminates and not stmt.orelse:
                self.meth.acquires.append((acquired, held, stmt.lineno))
                self.walk(stmt.body, held)
                return held | {acquired}
            self._expr(test, held)
            self.walk(stmt.body, held)
            self.walk(stmt.orelse, held)
            return held
        if isinstance(stmt, ast.Try):
            # a finally that releases X covers the acquire/try/finally
            # idiom: the try body holds X, statements after the Try
            # do not
            released: set[str] = set()
            for fs in stmt.finalbody:
                if isinstance(fs, ast.Expr):
                    lk = self._acquire_call(fs.value, "release")
                    if lk is not None:
                        released.add(lk)
            self.walk(stmt.body, held)
            for h in stmt.handlers:
                self.walk(h.body, held)
            self.walk(stmt.orelse, held)
            self.walk(stmt.finalbody, held - released)
            return held - released
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._expr(stmt.iter, held)
            self._target_write(stmt.target, held)
            self.walk(stmt.body, held)
            self.walk(stmt.orelse, held)
            return held
        if isinstance(stmt, ast.While):
            self._expr(stmt.test, held)
            self.walk(stmt.body, held)
            self.walk(stmt.orelse, held)
            return held
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a nested def runs later, in an unknown lock context
            self.walk(stmt.body, frozenset())
            return held
        if isinstance(stmt, ast.ClassDef):
            return held
        if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (stmt.targets if isinstance(stmt, ast.Assign)
                       else [stmt.target])
            for t in targets:
                self._target_write(t, held)
            if stmt.value is not None:
                self._expr(stmt.value, held)
            return held
        if isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                self._target_write(t, held)
            return held
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self._expr(child, held)
            elif isinstance(child, ast.stmt):
                held = self._stmt(child, held)
        return held

    def _target_write(self, t: ast.expr, held: frozenset[str]) -> None:
        if isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                self._target_write(e, held)
            return
        base = t
        if isinstance(t, ast.Subscript):
            base = t.value
            self._expr(t.slice, held)
        attr = _self_attr(base)
        if attr is not None:
            self.meth.writes.append((attr, t.lineno, held))
        else:
            self._expr(base, held)

    def _expr(self, node: ast.expr | None, held: frozenset[str]) -> None:
        if node is None:
            return
        call_funcs: set[int] = set()
        for n in ast.walk(node):
            if not isinstance(n, ast.Call):
                continue
            call_funcs.add(id(n.func))
            self.meth.calls.append((n, held))
            f = n.func
            if isinstance(f, ast.Attribute):
                recv_attr = _self_attr(f.value)
                if isinstance(f.value, ast.Name) and f.value.id == "self":
                    self.meth.self_calls.append((f.attr, held, n.lineno))
                elif f.attr in _MUTATORS and recv_attr is not None:
                    # self.X.append(...) — a content write to self.X
                    self.meth.writes.append((recv_attr, n.lineno, held))
                else:
                    self.meth.obj_calls.append((f.attr, held, n.lineno))
        # self.m referenced as a VALUE (stored, passed to partial(),
        # handed to an executor) escapes lock inference; self.m(...)
        # invoked directly — even nested inside another call's argument
        # list — does not.
        for n in ast.walk(node):
            if not isinstance(n, ast.Call):
                continue
            for arg in list(n.args) + [k.value for k in n.keywords]:
                for sub in ast.walk(arg):
                    a = _self_attr(sub)
                    if a is not None and isinstance(sub.ctx, ast.Load) \
                            and id(sub) not in call_funcs:
                        self.cls.escaped.add(a)


class LockPass:
    """Whole-run lock analysis. feed() per file, finish() at the end."""

    def __init__(self):
        self.classes: list[_Class] = []
        self.findings: list[Finding] = []
        # rel-path per class for reporting
        self._class_file: dict[int, str] = {}

    # -- per-file ----------------------------------------------------------
    def feed(self, sf: SourceFile) -> None:
        if sf.tree is None or not in_framework(sf.path):
            return
        # rel path, not the stem: every package has an __init__.py, and
        # stem-keyed module locks would merge across packages
        module = sf.rel
        module_locks = {
            t.id
            for node in sf.tree.body if isinstance(node, ast.Assign)
            for t in node.targets if isinstance(t, ast.Name)
            and _ctor_name(node.value) in (_LOCK_CTORS | _COND_CTORS)
        }
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ClassDef):
                self._feed_class(sf, node, module, module_locks)

    def _feed_class(self, sf: SourceFile, node: ast.ClassDef, module: str,
                    module_locks: set[str]) -> None:
        bases = []
        for b in node.bases:
            if isinstance(b, ast.Name):
                bases.append(b.id)
            elif isinstance(b, ast.Attribute):
                bases.append(b.attr)
        cls = _Class(module, node.name, bases)
        # lock/exempt attribute discovery, over every method
        for m in node.body:
            if not isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for st in ast.walk(m):
                if not isinstance(st, ast.Assign):
                    continue
                ctor = _ctor_name(st.value)
                if ctor is None:
                    continue
                for t in st.targets:
                    attr = _self_attr(t)
                    if attr is None:
                        continue
                    if ctor in _LOCK_CTORS:
                        cls.locks[attr] = attr
                    elif ctor in _COND_CTORS:
                        arg = st.value.args[0] if st.value.args else None
                        under = _self_attr(arg) if arg is not None else None
                        # Condition(self._lock) aliases the lock;
                        # Condition() owns its (R)Lock
                        cls.locks[attr] = cls.locks.get(under, under) \
                            if under else attr
                    if ctor in _EXEMPT_CTORS:
                        cls.exempt_attrs.add(attr)
        for m in node.body:
            if not isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            meth = _Method(m.name, m)
            meth.annotated = self._annotation(sf, cls, m)
            cls.methods[m.name] = meth
            _MethodWalker(cls, meth, module_locks, sf).walk(
                m.body, frozenset())
        self.classes.append(cls)
        self._class_file[id(cls)] = sf.rel

    def _annotation(self, sf: SourceFile, cls: _Class,
                    m: ast.AST) -> frozenset[str]:
        """`# gl: holds self._lock[, self._other]` on the def line (or
        the line above it) grants held locks the analyzer cannot see."""
        out: set[str] = set()
        for line in (m.lineno, m.lineno - 1):
            g = _GL_HOLDS_RE.search(sf.comments.get(line, ""))
            if g is None:
                continue
            for name in re.split(r"[\s,]+", g.group("locks").strip()):
                name = name.split(".")[-1]
                if name:
                    out.add(cls.locks.get(name, name))
        return frozenset(out)

    # -- whole-run ---------------------------------------------------------
    def finish(self) -> list[Finding]:
        self._merge_inherited_locks()
        for cls in self.classes:
            self._propagate(cls)
            self._check_gl001(cls)
        self._check_gl002()
        self.findings.sort(key=lambda f: (f.path, f.line, f.code))
        return self.findings

    def _merge_inherited_locks(self) -> None:
        by_name: dict[str, list[_Class]] = {}
        for c in self.classes:
            by_name.setdefault(c.name, []).append(c)
        for c in self.classes:
            for b in c.bases:
                for base in by_name.get(b, []):
                    for attr, canon in base.locks.items():
                        c.locks.setdefault(attr, canon)
                    c.exempt_attrs |= base.exempt_attrs

    def _propagate(self, cls: _Class) -> None:
        """Fixpoint: a private, non-escaped method called only under L
        is analyzed as holding L. Call sites inside __init__/__del__
        (or methods reachable only from them) don't constrain the
        intersection — the object is not shared during construction —
        and a method whose EVERY caller is construction-time is itself
        construction-exempt."""
        top = frozenset(set(cls.locks.values()))
        eligible = {
            n for n, m in cls.methods.items()
            if n.startswith("_") and not n.startswith("__")
            and n not in cls.escaped
            and any(n == c for meth in cls.methods.values()
                    for c, _, _ in meth.self_calls)
        }
        # construction-only fixpoint first: exempt status feeds the
        # lock-inheritance intersection below
        for _ in range(len(cls.methods) + 1):
            changed = False
            for n in eligible:
                callers = {meth.name for meth in cls.methods.values()
                           if any(c == n for c, _, _ in meth.self_calls)
                           and meth.name != n}
                only_ctor = bool(callers) and all(
                    cls.methods[c].exempt and not c.endswith("_locked")
                    for c in callers if c in cls.methods)
                if only_ctor != cls.methods[n].construction_only:
                    cls.methods[n].construction_only = only_ctor
                    changed = True
            if not changed:
                break
        inherited = {n: top for n in eligible}
        for _ in range(len(cls.methods) + 1):
            changed = False
            for n in eligible:
                seen: frozenset[str] | None = None
                for meth in cls.methods.values():
                    if meth.name in ("__init__", "__del__") or \
                            meth.construction_only:
                        continue  # pre-sharing call sites don't count
                    eff_caller = self._effective(cls, meth, inherited)
                    for callee, held, _ in meth.self_calls:
                        if callee != n:
                            continue
                        site = held | eff_caller
                        seen = site if seen is None else (seen & site)
                new = seen if seen is not None else frozenset()
                if new != inherited[n]:
                    inherited[n] = new
                    changed = True
            if not changed:
                break
        for n, m in cls.methods.items():
            m.inherited = inherited.get(n, frozenset()) | m.annotated

    def _effective(self, cls: _Class, meth: _Method,
                   inherited: dict[str, frozenset[str]]) -> frozenset[str]:
        return inherited.get(meth.name, frozenset()) | meth.annotated

    def _check_gl001(self, cls: _Class) -> None:
        if not cls.locks:
            return
        rel = self._class_file[id(cls)]
        sites: dict[str, list[tuple[int, frozenset[str], str]]] = {}
        for m in cls.methods.values():
            if m.exempt:
                continue
            for attr, line, held in m.writes:
                if attr in cls.exempt_attrs or attr in cls.locks:
                    continue
                eff = frozenset(held | m.inherited)
                sites.setdefault(attr, []).append((line, eff, m.name))
        for attr, ws in sorted(sites.items()):
            if len(ws) < 2:
                continue
            guarded = [w for w in ws if w[1]]
            naked = [w for w in ws if not w[1]]
            if not guarded:
                continue
            locks_used = sorted({lk for _, h, _ in guarded for lk in h})
            if naked and len(guarded) >= len(naked):
                for line, _, mname in sorted(naked):
                    self.findings.append(Finding(
                        rel, line, "GL001",
                        f"write to self.{attr} in {cls.name}.{mname} "
                        f"outside any lock (guarded by "
                        f"{'/'.join(locks_used)} at {len(guarded)} other "
                        f"site(s))"))
                continue
            if naked:
                continue  # mostly-naked attr: not lock-associated
            common = frozenset.intersection(*(h for _, h, _ in guarded))
            if common:
                continue
            # inconsistent guards: no single lock covers every write —
            # flag the sites missing the best-covering lock
            cover = sorted(
                ((sum(1 for _, h, _ in guarded if lk in h), lk)
                 for lk in locks_used), key=lambda t: (-t[0], t[1]))
            best = cover[0][1]
            for line, h, mname in sorted(guarded):
                if best not in h:
                    self.findings.append(Finding(
                        rel, line, "GL001",
                        f"write to self.{attr} in {cls.name}.{mname} "
                        f"holds {'/'.join(sorted(h))} but not {best}, "
                        f"which guards {cover[0][0]} other write(s) "
                        f"(no common lock)"))

    # -- GL002 --------------------------------------------------------------
    def _lock_summary(self) -> dict[int, frozenset[str]]:
        """Per-class transitive 'locks this class may acquire' node ids."""
        out: dict[int, frozenset[str]] = {}
        for cls in self.classes:
            acq = {cls.node_id(lk)
                   for m in cls.methods.values() for lk, _, _ in m.acquires}
            out[id(cls)] = frozenset(acq)
        return out

    def _check_gl002(self) -> None:
        edges: dict[tuple[str, str], tuple[str, int]] = {}

        def add_edge(a: str, b: str, rel: str, line: int) -> None:
            if a != b and (a, b) not in edges:
                edges[(a, b)] = (rel, line)

        # methods-by-name with per-class lock summaries, for one level
        # of cross-object resolution
        method_locks: dict[str, list[tuple[_Class, frozenset[str]]]] = {}
        for cls in self.classes:
            for n, m in cls.methods.items():
                acq = frozenset(cls.node_id(lk) for lk, _, _ in m.acquires)
                if acq:
                    method_locks.setdefault(n, []).append((cls, acq))
        for cls in self.classes:
            rel = self._class_file[id(cls)]
            for m in cls.methods.values():
                base = m.inherited
                for lk, held, line in m.acquires:
                    for h in held | base:
                        add_edge(cls.node_id(h), cls.node_id(lk), rel, line)
                for name, held, line in m.obj_calls:
                    eff = held | base
                    if not eff:
                        continue
                    owners = method_locks.get(name, [])
                    if not owners or len(owners) > 2:
                        continue  # unknown or too generic to resolve
                    for other, acq in owners:
                        if other is cls:
                            continue
                        for h in eff:
                            for b in acq:
                                add_edge(cls.node_id(h), b, rel, line)
        # cycle detection (DFS over the edge set)
        graph: dict[str, list[str]] = {}
        for (a, b) in edges:
            graph.setdefault(a, []).append(b)
        reported: set[frozenset[str]] = set()
        for start in sorted(graph):
            path: list[str] = []
            on_path: set[str] = set()

            def dfs(node: str) -> None:
                if node in on_path:
                    cyc = path[path.index(node):] + [node]
                    key = frozenset(cyc)
                    if key in reported:
                        return
                    reported.add(key)
                    first = min(
                        (edges[(cyc[i], cyc[i + 1])], i)
                        for i in range(len(cyc) - 1))
                    (rel, line), _ = first
                    self.findings.append(Finding(
                        rel, line, "GL002",
                        "lock-order cycle (potential deadlock): "
                        + " -> ".join(cyc)))
                    return
                if node not in graph:
                    return
                on_path.add(node)
                path.append(node)
                for nxt in sorted(graph[node]):
                    dfs(nxt)
                path.pop()
                on_path.discard(node)

            dfs(start)
