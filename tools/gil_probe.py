"""Diagnose consumer-thread starvation during decode blocks.

The r3 finding this probes: gRPC-stream TTFT ran ~120 ms (one decode
block) above engine-level TTFT (PERF.md). Hypothesis: while the serving
loop blocks in a device call through the axon tunnel, the GIL (or
scheduler) starves the gRPC server/client socket threads. This script
measures localhost TCP round-trip latency between two Python threads
while a realistic 8B decode loop runs in a third — if busy-RTT jumps to
~block duration, the starvation is confirmed and the fix is a
scheduling yield in the decode loop; if it stays ~idle-RTT, look at the
transport instead.

Run ON THE CHIP BOX: env -u XLA_FLAGS -u JAX_PLATFORMS python tools/gil_probe.py
"""

import os, time, sys, threading, functools, socket, statistics
import jax, jax.numpy as jnp, numpy as np
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from gofr_tpu.models import llama
from gofr_tpu.models.common import LLAMA_CONFIGS
from bench import acquire_chip_lock, int8_random_params

# serialize with any other chip holder (bench.py / retry loop):
# concurrent TPU clients through the tunnel wedge it for hours
_chip_lock = acquire_chip_lock(section="probe")

cfg = LLAMA_CONFIGS["llama3-8b"]
batch, cache_len, K = 64, 1024, 4
params = int8_random_params(cfg, jax.random.PRNGKey(0))
cache = llama.init_cache(cfg, batch, cache_len, dtype=jnp.int8)
rope = llama.get_rope_tables(cfg, cache_len)
cache = cache._replace(lengths=jnp.full((batch,), 32, jnp.int32))
tokens = jnp.zeros((batch,), jnp.int32)

@functools.partial(jax.jit, donate_argnums=(3,))
def multistep(params, rope, tokens, cache):
    def body(carry, _):
        t, c = carry
        logits, c = llama.decode_step(params, cfg, t, c, rope)
        return (jnp.argmax(logits, -1).astype(jnp.int32), c), t
    (t, c), toks = jax.lax.scan(body, (tokens, cache), None, length=K)
    return t, c, toks

tokens, cache, toks = multistep(params, rope, tokens, cache); np.asarray(toks)
print("compiled", flush=True)

stop = threading.Event()
def decode_loop():
    global tokens, cache
    while not stop.is_set():
        t, c, tk = multistep(params, rope, tokens, cache)
        tokens, cache = t, c
        np.asarray(tk)   # the fetch the engine loop does

# localhost TCP echo pair
srv = socket.socket(); srv.bind(("127.0.0.1", 0)); srv.listen(1)
port = srv.getsockname()[1]
def echo():
    conn, _ = srv.accept()
    conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    while True:
        d = conn.recv(64)
        if not d: return
        conn.sendall(d)
threading.Thread(target=echo, daemon=True).start()
cli = socket.create_connection(("127.0.0.1", port))
cli.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

def rtt_samples(n=40):
    out = []
    for _ in range(n):
        t0 = time.perf_counter()
        cli.sendall(b"x"); cli.recv(64)
        out.append((time.perf_counter() - t0) * 1e3)
        time.sleep(0.01)
    return out

idle = rtt_samples()
print(f"idle RTT p50={statistics.median(idle):.2f}ms max={max(idle):.2f}ms", flush=True)

th = threading.Thread(target=decode_loop, daemon=True); th.start()
time.sleep(1.0)
busy = rtt_samples()
stop.set(); th.join(timeout=30)
print(f"busy RTT p50={statistics.median(busy):.2f}ms max={max(busy):.2f}ms", flush=True)
