#!/usr/bin/env python
"""A/B profile the fused XLA decode path vs the flash-decode kernel.

VERDICT r4 #5: if flash-decode loses its hardware A/B a third time,
capture profiler traces of BOTH paths and write the postmortem. This
tool runs each path for a handful of fused blocks under
``jax.profiler.trace`` and saves the traces side by side:

    /tmp/gofr_flash_ab/xla/      the jnp/XLA fused-block path
    /tmp/gofr_flash_ab/flash/    the Pallas flash-decode path

Open with TensorBoard (or xprof) elsewhere; the trace contains per-HLO
timing, DMA sizes, and MXU/VPU occupancy — enough to attribute the gap
(per-grid-step overhead vs DMA-skip benefit vs scheduling slack).

Also prints the same wall-clock A/B bench.py reports, so the traces
and the numbers come from the same run. Holds the chip lock.

--mesh runs a different A/B: the shard_map'd mesh kernels (interpret
mode, GOFR_FLASH_INTERPRET=1) vs the jnp mesh reference, on tp=2 and
tp=4 factorizations of a virtual 8-device CPU mesh — no chip, no lock.
Token-exactness is gated STRICTLY (exit 1 on any mismatch or on a
silent fallback — the sharded kernel forms must actually dispatch);
CPU wall-clock numbers are ADVISORY only (interpret-mode emulation
says nothing about TPU perf; the device A/B above is the perf record).
The last stdout line is the JSON summary; --json-out also writes it to
a file (KERNEL_MESH_BENCH.json in CI / the committed record).

Usage:  python tools/flash_ab_profile.py [--cpu] [--batch 64]
        [--cache-len 1024] [--blocks 6]
        python tools/flash_ab_profile.py --mesh [--tp 2,4]
        [--json-out KERNEL_MESH_BENCH.json]
"""

from __future__ import annotations

import argparse
import functools
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))
sys.path.insert(0, ".")

if "--mesh" in sys.argv[1:]:
    # virtual 8-device CPU mesh, same bootstrap as tests/conftest.py —
    # must land before the first jax import (bench imports jax)
    os.environ["GOFR_BENCH_CPU"] = "1"
    if "--xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8").strip()

import bench  # noqa: E402


def run_path(name: str, multistep, params, rope, tokens, cache, blocks,
             trace_dir):
    import jax
    import numpy as np

    # warm (compile + first block) outside the trace
    tokens2, cache = multistep(params, rope, tokens, cache)
    np.asarray(tokens2)
    t0 = time.perf_counter()
    with jax.profiler.trace(trace_dir):
        for _ in range(blocks):
            tokens2, cache = multistep(params, rope, tokens2, cache)
        np.asarray(tokens2)
    dt = time.perf_counter() - t0
    return dt, cache


MESH_PROMPTS = [[5, 17, 42, 7], [3, 1, 4, 1, 5, 9, 2, 6]]
MESH_NEW_TOKENS = 24


def _counted(module, name, counts):
    """Wrap module.name with a dispatch counter (trace-time proof the
    shard_map'd kernel form ran — exactness alone can't tell a kernel
    from a silent fallback to the identical-numerics reference)."""
    inner = getattr(module, name)

    def wrapper(*a, **kw):
        counts[name] = counts.get(name, 0) + 1
        return inner(*a, **kw)

    setattr(module, name, wrapper)


def _mesh_engine_arm(cfg, params, mesh, *, paged, env):
    """One engine arm: set env, build, generate (single-stream greedy —
    batched streams can flip borderline argmax between factorizations),
    time a warm repeat. Returns (token lists, advisory ms/token)."""
    import jax.numpy as jnp

    from gofr_tpu.tpu import GenerationEngine

    saved = {k: os.environ.get(k) for k in env}
    os.environ.update({k: v for k, v in env.items() if v is not None})
    for k, v in env.items():
        if v is None:
            os.environ.pop(k, None)
    try:
        extra = dict(paged_blocks=25, paged_block_size=8) if paged else {}
        eng = GenerationEngine(cfg, params, slots=4, max_seq=64,
                               prompt_buckets=(8, 16), mesh=mesh,
                               kv_dtype=jnp.int8, **extra)
        try:
            toks = [eng.generate(p, max_new_tokens=MESH_NEW_TOKENS).tokens()
                    for p in MESH_PROMPTS]
            t0 = time.perf_counter()  # warm: prompt 0's bucket is compiled
            eng.generate(MESH_PROMPTS[0],
                         max_new_tokens=MESH_NEW_TOKENS).tokens()
            ms = (time.perf_counter() - t0) / MESH_NEW_TOKENS * 1e3
        finally:
            eng.close()
        return toks, ms
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def mesh_main(args):
    import json

    import jax

    from gofr_tpu.models import llama
    from gofr_tpu.models.common import LLAMA_CONFIGS
    from gofr_tpu.ops import flash, flash_decode, paged_attention
    from gofr_tpu.parallel import make_mesh, shard_params

    bench.init_backend()
    n_dev = len(jax.devices())
    counts = {}
    _counted(flash, "flash_prefill_sharded", counts)
    _counted(flash_decode, "flash_decode_sharded", counts)
    _counted(paged_attention, "paged_decode_sharded", counts)

    tiny = LLAMA_CONFIGS["tiny"]                       # n_kv_heads=2
    cfgs = {2: tiny, 4: tiny.with_(name="tiny4", n_kv_heads=4)}
    params = {tp: llama.init(cfgs[tp], jax.random.PRNGKey(1))
              for tp in cfgs}

    # kernel arm env; the jnp arm clears all three (on CPU without
    # interpret every *_auto dispatcher takes the reference path)
    kernel_env = {"GOFR_FLASH_INTERPRET": "1", "GOFR_FLASH_DECODE": "1",
                  "GOFR_FLASH_DECODE_FORCE": "1"}
    jnp_env = {k: None for k in kernel_env}

    arms = []
    for tp in (int(t) for t in args.tp.split(",")):
        cfg = cfgs[tp]
        mesh = make_mesh(tp=tp, dp=n_dev // tp)
        sharded = shard_params(params[tp], mesh)
        for engine in ("contiguous", "paged"):
            paged = engine == "paged"
            ref, ref_ms = _mesh_engine_arm(cfg, sharded, mesh,
                                           paged=paged, env=jnp_env)
            got, ker_ms = _mesh_engine_arm(cfg, sharded, mesh,
                                           paged=paged, env=kernel_env)
            arm = {"tp": tp, "engine": engine, "kv": "int8",
                   "jnp_ms_per_tok": round(ref_ms, 3),
                   "kernel_ms_per_tok": round(ker_ms, 3),
                   "tokens_exact": got == ref}
            arms.append(arm)
            print(f"tp={tp} {engine}: jnp {ref_ms:.2f} ms/tok, "
                  f"kernel {ker_ms:.2f} ms/tok (advisory), "
                  f"exact={arm['tokens_exact']}", flush=True)

    ok = (all(a["tokens_exact"] for a in arms)
          and all(counts.get(k, 0) > 0 for k in
                  ("flash_prefill_sharded", "flash_decode_sharded",
                   "paged_decode_sharded")))
    summary = {"bench": "mesh_kernels", "backend": "cpu-interpret",
               "devices": n_dev, "timings_advisory": True,
               "arms": arms, "kernels_dispatched": counts, "ok": ok}
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(summary, f, indent=1, sort_keys=True)
            f.write("\n")
    print(json.dumps(summary, sort_keys=True), flush=True)
    if not ok:
        sys.exit(1)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--cache-len", type=int, default=1024)
    ap.add_argument("--blocks", type=int, default=6)
    ap.add_argument("--decode-block", type=int, default=8)
    ap.add_argument("--out", default="/tmp/gofr_flash_ab")
    ap.add_argument("--mesh", action="store_true",
                    help="A/B shard_map'd mesh kernels (interpret) vs the "
                         "jnp mesh reference on a virtual CPU mesh")
    ap.add_argument("--tp", default="2,4",
                    help="comma-separated tp factors for --mesh")
    ap.add_argument("--json-out", default=None,
                    help="also write the --mesh JSON summary here")
    args = ap.parse_args()
    if args.mesh:
        return mesh_main(args)

    import jax
    import jax.numpy as jnp

    from gofr_tpu.models import llama
    from gofr_tpu.models.common import LLAMA_CONFIGS

    bench.init_backend()
    cfg = LLAMA_CONFIGS["tiny" if args.cpu else "llama3-8b"]
    params = bench.int8_random_params(cfg, jax.random.PRNGKey(0))
    rope = llama.get_rope_tables(cfg, args.cache_len)

    def make(flash: bool):
        @functools.partial(jax.jit, donate_argnums=(3,))
        def multistep(params, rope, tokens, cache):
            def body(carry, _):
                tokens, cache = carry
                logits, cache = llama.decode_step(params, cfg, tokens,
                                                  cache, rope, flash=flash)
                tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                return (tok, cache), tok

            (tokens, cache), _ = jax.lax.scan(body, (tokens, cache),
                                              None, length=args.decode_block)
            return tokens, cache

        return multistep

    results = {}
    for name, flash in (("xla", False), ("flash", True)):
        cache = llama.init_cache(cfg, args.batch, args.cache_len,
                                 dtype=jnp.int8)
        cache = cache._replace(lengths=jnp.full((args.batch,),
                                                args.cache_len // 2,
                                                jnp.int32))
        tokens = jnp.zeros((args.batch,), jnp.int32)
        dt, cache = run_path(name, make(flash), params, rope, tokens,
                             cache, args.blocks,
                             os.path.join(args.out, name))
        n = args.blocks * args.decode_block
        results[name] = dt / n * 1e3
        print(f"{name}: {dt / n * 1e3:.2f} ms/step "
              f"({args.batch * n / dt:.0f} tok/s), trace in "
              f"{os.path.join(args.out, name)}", flush=True)
        del cache

    faster = min(results, key=results.get)
    print(f"winner: {faster} "
          f"({results[faster]:.2f} vs "
          f"{results[max(results, key=results.get)]:.2f} ms/step)")


if __name__ == "__main__":
    # serialize with any other chip holder (bench.py / retry loop):
    # concurrent TPU clients through the tunnel wedge it for hours.
    # --mesh is CPU-only emulation — no chip, no lock to hold.
    if "--mesh" not in sys.argv[1:]:
        _chip_lock = bench.acquire_chip_lock(section="probe")
    main()
