#!/usr/bin/env python
"""A/B profile the fused XLA decode path vs the flash-decode kernel.

VERDICT r4 #5: if flash-decode loses its hardware A/B a third time,
capture profiler traces of BOTH paths and write the postmortem. This
tool runs each path for a handful of fused blocks under
``jax.profiler.trace`` and saves the traces side by side:

    /tmp/gofr_flash_ab/xla/      the jnp/XLA fused-block path
    /tmp/gofr_flash_ab/flash/    the Pallas flash-decode path

Open with TensorBoard (or xprof) elsewhere; the trace contains per-HLO
timing, DMA sizes, and MXU/VPU occupancy — enough to attribute the gap
(per-grid-step overhead vs DMA-skip benefit vs scheduling slack).

Also prints the same wall-clock A/B bench.py reports, so the traces
and the numbers come from the same run. Holds the chip lock.

Usage:  python tools/flash_ab_profile.py [--cpu] [--batch 64]
        [--cache-len 1024] [--blocks 6]
"""

from __future__ import annotations

import argparse
import functools
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))
sys.path.insert(0, ".")

import bench  # noqa: E402


def run_path(name: str, multistep, params, rope, tokens, cache, blocks,
             trace_dir):
    import jax
    import numpy as np

    # warm (compile + first block) outside the trace
    tokens2, cache = multistep(params, rope, tokens, cache)
    np.asarray(tokens2)
    t0 = time.perf_counter()
    with jax.profiler.trace(trace_dir):
        for _ in range(blocks):
            tokens2, cache = multistep(params, rope, tokens2, cache)
        np.asarray(tokens2)
    dt = time.perf_counter() - t0
    return dt, cache


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--cache-len", type=int, default=1024)
    ap.add_argument("--blocks", type=int, default=6)
    ap.add_argument("--decode-block", type=int, default=8)
    ap.add_argument("--out", default="/tmp/gofr_flash_ab")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from gofr_tpu.models import llama
    from gofr_tpu.models.common import LLAMA_CONFIGS

    bench.init_backend()
    cfg = LLAMA_CONFIGS["tiny" if args.cpu else "llama3-8b"]
    params = bench.int8_random_params(cfg, jax.random.PRNGKey(0))
    rope = llama.get_rope_tables(cfg, args.cache_len)

    def make(flash: bool):
        @functools.partial(jax.jit, donate_argnums=(3,))
        def multistep(params, rope, tokens, cache):
            def body(carry, _):
                tokens, cache = carry
                logits, cache = llama.decode_step(params, cfg, tokens,
                                                  cache, rope, flash=flash)
                tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                return (tok, cache), tok

            (tokens, cache), _ = jax.lax.scan(body, (tokens, cache),
                                              None, length=args.decode_block)
            return tokens, cache

        return multistep

    results = {}
    for name, flash in (("xla", False), ("flash", True)):
        cache = llama.init_cache(cfg, args.batch, args.cache_len,
                                 dtype=jnp.int8)
        cache = cache._replace(lengths=jnp.full((args.batch,),
                                                args.cache_len // 2,
                                                jnp.int32))
        tokens = jnp.zeros((args.batch,), jnp.int32)
        dt, cache = run_path(name, make(flash), params, rope, tokens,
                             cache, args.blocks,
                             os.path.join(args.out, name))
        n = args.blocks * args.decode_block
        results[name] = dt / n * 1e3
        print(f"{name}: {dt / n * 1e3:.2f} ms/step "
              f"({args.batch * n / dt:.0f} tok/s), trace in "
              f"{os.path.join(args.out, name)}", flush=True)
        del cache

    faster = min(results, key=results.get)
    print(f"winner: {faster} "
          f"({results[faster]:.2f} vs "
          f"{results[max(results, key=results.get)]:.2f} ms/step)")


if __name__ == "__main__":
    # serialize with any other chip holder (bench.py / retry loop):
    # concurrent TPU clients through the tunnel wedge it for hours
    _chip_lock = bench.acquire_chip_lock(section="probe")
    main()
