#!/usr/bin/env python
"""SLO-class scheduling + chunked-prefill benchmark: proves the serving
scheduler closes the long-prefill TTFT gap and degrades classes in
order under overload.

CPU-only (JAX_PLATFORMS=cpu, tiny model, no chip lock): the point is
the RATIO between scheduling policies on identical hardware, not
absolute chip numbers. Two parts, one process, one run:

PART A — chunked prefill A/B (both arms in this run):
  head_of_line   TPU_PREFILL_CHUNK=0 — a long prompt's chunks dispatch
                 back-to-back; a newly arrived short request waits out
                 the WHOLE prefill and active decode streams stall
  chunked        default — bounded chunk dispatches with one admission
                 pass + one decode block between chunks

  Load per arm: continuous long-prompt throughput-class streams
  (the head-of-line hazard) while short latency-class probes arrive on
  a fixed cadence. Measured: latency-class TTFT (submit -> first
  token) and the long streams' decode inter-token gaps.

PART B — 2x overload with mixed classes (gate + class degradation +
the latency slot reserve):
  uncontended    latency-only at 0.15x measured capacity — the
                 reference tail
  overload       the same latency rate + 1.85x capacity of
                 throughput-class (2x total) through an AdmissionGate
                 with throughput_factor 0.5 — throughput must shed
                 FIRST and latency-class TTFT must hold near its
                 uncontended value (the reserved slot is what makes
                 that physically possible: admitted batch streams can
                 never occupy every slot)

Acceptance (checks; gated in --smoke too):
  - latency-class TTFT p50 improves >= 25% chunked vs head_of_line
  - decode inter-token p99 regresses <= 10% (it should IMPROVE:
    head-of-line stalls decode entirely during a long prefill)
  - under overload, throughput-class sheds dominate (latency sheds
    stay near zero) and the latency tail holds: p95 within
    max(1.3x, +50 ms noise floor) of uncontended — p99 and the raw
    1.3x ratio are recorded; on CPU the uncontended p99 sits at ~one
    decode block, so the bare ratio measures box jitter (a device
    run is where the strict 1.3x p99 criterion is judged)

Output follows the bench stdout contract (tools/README.md): the LAST
stdout line is the JSON artifact; earlier stdout lines are partial
snapshots; progress goes to stderr. Full runs write SLO_BENCH.json.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import threading
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402
import numpy as np  # noqa: E402

from gofr_tpu.errors import TooManyRequests  # noqa: E402
from gofr_tpu.models import LLAMA_CONFIGS, llama  # noqa: E402
from gofr_tpu.resilience import (AdmissionGate, SLO_LATENCY,  # noqa: E402
                                 SLO_THROUGHPUT)
from gofr_tpu.tpu import GenerationEngine  # noqa: E402


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def pctl(vals, p):
    if not vals:
        return None
    vs = sorted(vals)
    return vs[min(len(vs) - 1, int(p / 100.0 * len(vs)))]


BUCKETS = (8, 16, 32)
MAX_SEQ = 512
LONG_LEN = 480      # ~15 mid chunks at the default 32-token budget
SHORT_LEN = 6


class Harness:
    def __init__(self):
        self.cfg = dataclasses.replace(LLAMA_CONFIGS["tiny"],
                                       max_seq=MAX_SEQ)
        self.params = llama.init(self.cfg, jax.random.PRNGKey(1))
        self.rng = np.random.default_rng(42)

    def engine(self, **kw) -> GenerationEngine:
        kw.setdefault("slots", 4)
        kw.setdefault("max_seq", MAX_SEQ)
        kw.setdefault("prompt_buckets", BUCKETS)
        kw.setdefault("decode_block", 2)
        eng = GenerationEngine(self.cfg, self.params, **kw)
        eng.warmup()
        return eng

    def prompt(self, n: int):
        return self.rng.integers(1, self.cfg.vocab_size, n).tolist()


class LongLoad:
    """Keeps ``n`` concurrent long-prompt throughput-class streams
    alive against the engine and records their client-observed decode
    cadence — the stream a head-of-line prefill stalls.

    Gaps are taken per DECODE BLOCK (every ``decode_block``-th token):
    a fused block delivers its tokens back-to-back in one host loop,
    and the intra-burst ~0 gaps would dilute the percentile the bench
    gates on (the same rationale as the engine's reap-level
    ``app_tpu_inter_token_duration``)."""

    def __init__(self, harness: Harness, eng, n: int, max_new: int = 16):
        self.h = harness
        self.eng = eng
        self.max_new = max_new
        self.block = eng.decode_block
        self.itl: list[float] = []
        self.prefills = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._threads = [threading.Thread(target=self._drive, daemon=True)
                         for _ in range(n)]

    def _drive(self) -> None:
        while not self._stop.is_set():
            prompt = self.h.prompt(LONG_LEN)
            try:
                stream = self.eng.generate(prompt,
                                           max_new_tokens=self.max_new,
                                           slo_class=SLO_THROUGHPUT)
            except Exception:
                time.sleep(0.01)
                continue
            gaps, prev = [], None
            for i, _ in enumerate(stream):
                if i % self.block:
                    continue  # intra-burst delivery, not device cadence
                now = time.monotonic()
                if prev is not None:
                    gaps.append(now - prev)
                prev = now
            with self._lock:
                self.itl.extend(gaps)
                self.prefills += 1

    def __enter__(self) -> "LongLoad":
        for t in self._threads:
            t.start()
        return self

    def __exit__(self, *exc) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=30.0)


def ttft_of(stream) -> float:
    return stream.trace["first_put"] - stream.trace["submit"]


def run_arm(h: Harness, name: str, probes: int, interval: float,
            **engine_kw) -> dict:
    """One Part-A arm: long-prefill background load + short
    latency-class TTFT probes."""
    log(f"slo_bench: arm {name}: building engine")
    eng = h.engine(**engine_kw)
    ttfts = []
    try:
        # 3 cycling long streams: each spends most of its life in
        # prefill (480 tokens vs 8 decoded), so most probes arrive
        # while a lattice is actually running — the hazard under test
        with LongLoad(h, eng, n=3) as load:
            time.sleep(0.2)  # let the first long prefills start
            for _ in range(probes):
                stream = eng.generate(h.prompt(SHORT_LEN),
                                      max_new_tokens=4,
                                      slo_class=SLO_LATENCY)
                stream.tokens()  # drain: the probe slot must retire
                ttfts.append(ttft_of(stream))
                time.sleep(interval)
        itl, prefills = list(load.itl), load.prefills
    finally:
        eng.close()
    out = {
        "probes": len(ttfts),
        "long_prefills": prefills,
        "ttft_p50_ms": round((pctl(ttfts, 50) or 0) * 1e3, 2),
        "ttft_p99_ms": round((pctl(ttfts, 99) or 0) * 1e3, 2),
        "itl_samples": len(itl),
        "itl_p50_ms": round((pctl(itl, 50) or 0) * 1e3, 3),
        "itl_p99_ms": round((pctl(itl, 99) or 0) * 1e3, 3),
    }
    log(f"slo_bench: arm {name}: {out}")
    return out


def measure_capacity(h: Harness, eng, seconds: float) -> float:
    """Closed-loop short-request capacity (requests/s): one worker per
    slot, no queueing — what this box actually completes."""
    stop = time.monotonic() + seconds
    counts = [0] * eng.n_slots

    def worker(i: int) -> None:
        while time.monotonic() < stop:
            eng.generate(h.prompt(SHORT_LEN), max_new_tokens=16).tokens()
            counts[i] += 1

    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(len(counts))]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=seconds + 30.0)
    return sum(counts) / (time.monotonic() - t0)


class Phase:
    """Open-loop mixed-class load driven by a FIXED worker pool: each
    worker claims the next scheduled (offset, class) arrival and fires
    it at its offset. Thread-per-request spawn jitter would otherwise
    dominate the TTFT tails this phase exists to compare (the same
    lesson as chaos_bench's rate cap); a bounded pool keeps the
    arrival schedule honest while sheds return in microseconds."""

    WORKERS = 32

    def __init__(self, h: Harness, eng, lat_rps: float, thr_rps: float,
                 duration: float):
        self.h = h
        self.eng = eng
        self.lat_rps = lat_rps
        self.thr_rps = thr_rps
        self.duration = duration
        self.lock = threading.Lock()
        self.ttft = {SLO_LATENCY: [], SLO_THROUGHPUT: []}
        self.sheds = {SLO_LATENCY: 0, SLO_THROUGHPUT: 0}
        self.late = 0  # arrivals fired behind schedule (pool saturated)
        self.errors: list[str] = []

    def _one(self, cls: str) -> None:
        try:
            # heavier than the Part-A probes on purpose: more device
            # time per request keeps arrival rates (and the Python-side
            # churn that pollutes tail percentiles) low
            stream = self.eng.generate(self.h.prompt(SHORT_LEN),
                                       max_new_tokens=16, slo_class=cls)
            stream.tokens()
            t = ttft_of(stream)
        except TooManyRequests:
            with self.lock:
                self.sheds[cls] += 1
            return
        except Exception as e:  # noqa: BLE001 — tally, judge later
            with self.lock:
                self.errors.append(repr(e))
            return
        with self.lock:
            self.ttft[cls].append(t)

    def run(self) -> dict:
        # one merged seeded arrival schedule for both classes
        arrivals = []
        for cls, rate in ((SLO_LATENCY, self.lat_rps),
                          (SLO_THROUGHPUT, self.thr_rps)):
            if rate <= 0:
                continue
            n = max(1, int(rate * self.duration))
            arrivals += [(i / rate, cls) for i in range(n)]
        arrivals.sort()
        cursor = [0]
        t0 = time.monotonic()

        def worker() -> None:
            while True:
                with self.lock:
                    i = cursor[0]
                    if i >= len(arrivals):
                        return
                    cursor[0] = i + 1
                offset, cls = arrivals[i]
                pause = t0 + offset - time.monotonic()
                if pause > 0:
                    time.sleep(pause)
                elif pause < -0.05:
                    with self.lock:
                        self.late += 1
                self._one(cls)

        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(min(self.WORKERS, len(arrivals)))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=self.duration + 60.0)
        out = {"offered": len(arrivals), "late": self.late}
        for cls in (SLO_LATENCY, SLO_THROUGHPUT):
            out[cls] = {
                "completed": len(self.ttft[cls]),
                "sheds": self.sheds[cls],
                "ttft_p50_ms": round((pctl(self.ttft[cls], 50) or 0) * 1e3, 2),
                "ttft_p95_ms": round((pctl(self.ttft[cls], 95) or 0) * 1e3, 2),
                "ttft_p99_ms": round((pctl(self.ttft[cls], 99) or 0) * 1e3, 2),
            }
        out["errors"] = len(self.errors)
        return out


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--probes", type=int, default=60,
                    help="Part A latency-class TTFT probes per arm")
    ap.add_argument("--overload-s", type=float, default=8.0)
    ap.add_argument("--uncontended-s", type=float, default=4.0)
    ap.add_argument("--out", default=str(Path(__file__).resolve().parent.parent
                                         / "SLO_BENCH.json"))
    ap.add_argument("--smoke", action="store_true",
                    help="reduced CI run: no artifact file")
    args = ap.parse_args()
    if args.smoke:
        args.probes, args.overload_s, args.uncontended_s = 24, 6.0, 4.0

    h = Harness()
    result = {"bench": "slo_sched", "smoke": bool(args.smoke),
              "long_prompt": LONG_LEN, "buckets": list(BUCKETS)}

    # -- Part A: chunked-prefill A/B ------------------------------------
    interval = 0.03
    arms = {
        "head_of_line": run_arm(h, "head_of_line", args.probes, interval,
                                prefill_chunk=0),
        "chunked": run_arm(h, "chunked", args.probes, interval),
    }
    result["arms"] = arms
    hol, chk = arms["head_of_line"], arms["chunked"]
    ttft_gain = (1 - chk["ttft_p50_ms"] / hol["ttft_p50_ms"]
                 if hol["ttft_p50_ms"] else None)
    itl_ratio = (chk["itl_p99_ms"] / hol["itl_p99_ms"]
                 if hol["itl_p99_ms"] else None)
    result["chunking_checks"] = {
        "ttft_p50_improvement_pct": (round(ttft_gain * 100, 1)
                                     if ttft_gain is not None else None),
        "ttft_improves_25pct": bool(ttft_gain is not None
                                    and ttft_gain >= 0.25),
        "itl_p99_ratio": (round(itl_ratio, 3)
                          if itl_ratio is not None else None),
        "itl_p99_within_1p1x": bool(itl_ratio is not None
                                    and itl_ratio <= 1.10),
    }
    print(json.dumps({"partial": "overload pending", **result}), flush=True)

    # -- Part B: 2x overload, mixed classes -----------------------------
    log("slo_bench: overload: building gated engine")
    gate = AdmissionGate(max_queue_depth=8, throughput_factor=0.5,
                         brownout_delay=0.05, brownout_max_new=2,
                         name="generate")
    eng = h.engine(gate=gate)
    try:
        capacity = measure_capacity(h, eng, 1.5 if args.smoke else 3.0)
        log(f"slo_bench: measured capacity {capacity:.1f} rps")
        # mixed 2x: latency is the minority under a batch-driven
        # overload (0.15x capacity — within the reserved slot's own
        # capacity, so the reservation can actually honor the SLO);
        # throughput carries the excess to 2x total. The gate squeezes
        # throughput out while latency keeps near-uncontended service.
        uncontended = Phase(h, eng, lat_rps=0.15 * capacity, thr_rps=0.0,
                            duration=args.uncontended_s).run()
        overload = Phase(h, eng, lat_rps=0.15 * capacity,
                         thr_rps=1.85 * capacity,
                         duration=args.overload_s).run()
    finally:
        eng.close()
    result["overload"] = {
        "capacity_rps": round(capacity, 1),
        "uncontended": uncontended,
        "mixed_2x": overload,
        "gate": {k: gate.stats()[k]
                 for k in ("sheds", "sheds_by_class", "brownout_capped")},
    }
    lat_unc = uncontended[SLO_LATENCY]["ttft_p99_ms"]
    lat_over = overload[SLO_LATENCY]["ttft_p99_ms"]
    p99_ratio = lat_over / lat_unc if lat_unc else None
    thr_sheds = overload[SLO_THROUGHPUT]["sheds"]
    lat_sheds = overload[SLO_LATENCY]["sheds"]
    # Tail gate: overloaded latency tail within 1.3x of uncontended OR
    # an absolute scheduling-noise floor (50 ms), judged at p95. On this
    # CPU/GIL harness the uncontended p99 lands at ~one decode block
    # (a few ms), so a bare 1.3x bound is smaller than a single loop
    # hiccup — it would measure the box, not the scheduler; p99 and
    # the raw 1.3x ratio are always RECORDED so regressions stay
    # visible, and a device run (real service times, 10^4 samples) is
    # where the strict ratio is meaningful.
    unc_g = uncontended[SLO_LATENCY]["ttft_p95_ms"]
    over_g = overload[SLO_LATENCY]["ttft_p95_ms"]
    bound_ms = max(1.3 * unc_g, unc_g + 50.0) if unc_g else None
    gate_pctl = "p95 vs max(1.3x, +50ms floor)"
    result["overload_checks"] = {
        "throughput_shed_first": bool(thr_sheds > 0
                                      and thr_sheds > 5 * lat_sheds),
        "thr_sheds": thr_sheds,
        "lat_sheds": lat_sheds,
        "lat_p99_ratio_vs_uncontended": (round(p99_ratio, 3)
                                         if p99_ratio else None),
        "lat_tail_gate": gate_pctl,
        "lat_tail_ms": over_g,
        "lat_tail_bound_ms": round(bound_ms, 2) if bound_ms else None,
        "lat_tail_within_bound": bool(bound_ms is not None
                                      and over_g <= bound_ms),
    }

    # -- invariants (smoke-gated) + checks ------------------------------
    invariants = []
    for name, arm in arms.items():
        if arm["probes"] != args.probes:
            invariants.append(f"{name}: lost TTFT probes")
        if arm["long_prefills"] == 0 or arm["itl_samples"] == 0:
            invariants.append(f"{name}: background long load never ran")
    for phase_name, ph in (("uncontended", uncontended),
                           ("mixed_2x", overload)):
        acc = sum(ph[c]["completed"] + ph[c]["sheds"]
                  for c in (SLO_LATENCY, SLO_THROUGHPUT)) + ph["errors"]
        if acc != ph["offered"]:
            invariants.append(f"{phase_name}: {acc} accounted != "
                              f"{ph['offered']} offered")
        if ph["errors"]:
            invariants.append(f"{phase_name}: {ph['errors']} errors")
    if uncontended[SLO_LATENCY]["sheds"]:
        invariants.append("uncontended phase shed latency traffic")
    result["invariants_failed"] = invariants

    checks_ok = all(v for v in (
        result["chunking_checks"]["ttft_improves_25pct"],
        result["chunking_checks"]["itl_p99_within_1p1x"],
        result["overload_checks"]["throughput_shed_first"],
        result["overload_checks"]["lat_tail_within_bound"],
    ))
    ok = not invariants and checks_ok
    if not args.smoke and ok:
        Path(args.out).write_text(json.dumps(result, indent=1) + "\n")
        log(f"wrote {args.out}")
    print(json.dumps(result), flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
