#!/usr/bin/env python
"""Device-memory attribution report: who holds how much HBM.

Renders the hbm accounting registry (``gofr_tpu/tpu/hbm.py`` — the
table every GL202-checked allocation flows through) against
``jax.live_arrays()`` ground truth. Two modes:

  - attach mode (default when subsystems already accounted bytes in
    this process — e.g. imported from a notebook/REPL next to a live
    engine): report what the registry holds right now;
  - demo mode (the common CLI case, or ``--demo``): build a tiny CPU
    GenerationEngine with a prefix pool, serve a few requests, report
    with the engine live, then close it and report again — showing the
    release path works (the same reconciliation ``pytest --hbmwatch``
    gates on).

CPU-only by default (JAX_PLATFORMS honored if already set): the point
is attribution plumbing, not chip numbers — no chip lock taken.
Stdout contract (tools/README.md): the LAST line is the JSON
artifact; earlier lines are the human-readable table on stderr/stdout.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def table(att: dict) -> str:
    rows = [f"  {'subsystem':<14} {'bytes':>12}"]
    for sub, n in att["accounted"].items():
        rows.append(f"  {sub:<14} {n:>12}")
    rows.append(f"  {'(unattributed)':<14} {att['unattributed']:>12}")
    rows.append(f"  {'live total':<14} {att['live_bytes']:>12}")
    return "\n".join(rows)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description="HBM attribution report")
    ap.add_argument("--demo", action="store_true",
                    help="force the tiny-engine demo even if the "
                         "registry already has entries")
    ap.add_argument("--requests", type=int, default=3)
    args = ap.parse_args(argv)

    from gofr_tpu.testutil.hbmwatch import attribution
    from gofr_tpu.tpu import hbm

    artifact: dict = {"tool": "hbm_report"}
    demo = args.demo or not hbm.live_bytes()
    if demo:
        import jax
        import numpy as np

        from gofr_tpu.models import LLAMA_CONFIGS, llama
        from gofr_tpu.tpu import GenerationEngine

        log("hbm_report: demo mode — tiny engine + prefix pool, "
            f"{args.requests} request(s)")
        cfg = LLAMA_CONFIGS["tiny"]
        eng = GenerationEngine(cfg, llama.init(cfg, jax.random.PRNGKey(0)),
                               slots=2, max_seq=128,
                               prompt_buckets=(16, 32),
                               prefix_cache_slots=2,
                               prefix_store_min=16)
        try:
            rng = np.random.default_rng(0)
            for _ in range(max(1, args.requests)):
                prompt = rng.integers(1, cfg.vocab_size, size=24)
                eng.generate(prompt, max_new_tokens=4).tokens()
            att_live = attribution()
            log("attribution with engine live:")
            log(table(att_live))
            artifact["serving"] = att_live
        finally:
            eng.close()
        del eng
        import gc

        gc.collect()  # freed buffers must not read as live
        att_closed = attribution()
        log("attribution after close():")
        log(table(att_closed))
        artifact["after_close"] = att_closed
        artifact["released_ok"] = not att_closed["accounted"]
    else:
        att = attribution()
        log("attribution (attach mode):")
        log(table(att))
        artifact["serving"] = att
    print(json.dumps(artifact))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
