#!/usr/bin/env python
"""Device-memory attribution + arbiter report: who holds how much HBM,
under what lease, and how the arbiter behaves under pressure.

Renders the hbm accounting/arbiter registry (``gofr_tpu/tpu/hbm.py`` —
the table every GL202-checked allocation flows through, now the lease
book of the memory arbiter) against ``jax.live_arrays()`` ground
truth. Three modes:

  - attach mode (default when subsystems already accounted bytes in
    this process — e.g. imported from a notebook/REPL next to a live
    engine): report what the registry holds right now, including the
    live lease/reclaim table;
  - demo mode (the common CLI case, or ``--demo``): build a tiny CPU
    GenerationEngine with a prefix pool, serve a few requests, report
    with the engine live, then close it and report again — showing the
    release path works (the same reconciliation ``pytest --hbmwatch``
    gates on);
  - pressure mode (``--pressure``, the CI smoke arm with ``--smoke``):
    the memory-pressure acceptance run. One process, a deliberately
    tiny synthetic budget, a contiguous engine with prefix cache
    (T0 + host T1) PLUS a paged engine with spec decode: constructing
    the second engine must force the arbiter to shrink the first's T0
    pool (leases rebalance), a mixed workload under a seeded
    ``HBM_ALLOC`` storm must produce ONLY served 429 sheds (zero
    process deaths, zero non-shed errors, bounded shed rate), and
    post-storm serving must return token-exact and leak-flat. A
    passing full run commits ``HBM_BENCH.json``.

CPU-only by default (JAX_PLATFORMS honored if already set): the point
is attribution/arbitration plumbing, not chip numbers — no chip lock
taken. Stdout contract (tools/README.md): the LAST line is the JSON
artifact; earlier lines are the human-readable tables on stderr.
"""

from __future__ import annotations

import argparse
import gc
import json
import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def table(att: dict) -> str:
    rows = [f"  {'subsystem':<14} {'bytes':>12}"]
    for sub, n in att["accounted"].items():
        rows.append(f"  {sub:<14} {n:>12}")
    rows.append(f"  {'(unattributed)':<14} {att['unattributed']:>12}")
    rows.append(f"  {'live total':<14} {att['live_bytes']:>12}")
    return "\n".join(rows)


def lease_table(arb: dict) -> str:
    """The arbiter's live lease/reclaim book, human-shaped."""
    b = arb["budget_bytes"]
    rows = [f"  budget: {b if b is not None else '(off)'}  "
            f"in_use: {arb['in_use_bytes']}  "
            f"headroom: {arb['headroom_bytes']}"]
    rows.append(f"  {'subsystem':<12} {'tag':<8} {'bytes':>12} "
                f"{'priority':<8} reclaimable")
    for ls in arb["leases"]:
        rows.append(f"  {ls['subsystem']:<12} {ls['tag'] or '-':<8} "
                    f"{ls['bytes']:>12} {ls['priority']:<8} "
                    f"{'yes' if ls['reclaimable'] else 'no'}")
    if arb["reclaims"] or arb["sheds"] or arb["oom_retries"]:
        rows.append(f"  reclaims={arb['reclaims']} "
                    f"(freed {arb['reclaimed_bytes']}B) "
                    f"sheds={arb['sheds']} retries={arb['oom_retries']}")
    return "\n".join(rows)


def _tiny_params():
    import jax

    from gofr_tpu.models import LLAMA_CONFIGS, llama

    cfg = LLAMA_CONFIGS["tiny"]
    return cfg, llama.init(cfg, jax.random.PRNGKey(0))


def run_demo(requests: int, artifact: dict) -> None:
    import numpy as np

    from gofr_tpu.testutil.hbmwatch import attribution
    from gofr_tpu.tpu import GenerationEngine

    cfg, params = _tiny_params()
    log("hbm_report: demo mode — tiny engine + prefix pool, "
        f"{requests} request(s)")
    eng = GenerationEngine(cfg, params,
                           slots=2, max_seq=128,
                           prompt_buckets=(16, 32),
                           prefix_cache_slots=2,
                           prefix_store_min=16)
    try:
        rng = np.random.default_rng(0)
        for _ in range(max(1, requests)):
            prompt = rng.integers(1, cfg.vocab_size, size=24)
            eng.generate(prompt, max_new_tokens=4).tokens()
        att_live = attribution()
        log("attribution with engine live:")
        log(table(att_live))
        from gofr_tpu.tpu import hbm

        log("arbiter lease table:")
        log(lease_table(hbm.arbiter_stats()))
        artifact["serving"] = att_live
        artifact["arbiter"] = hbm.arbiter_stats()
    finally:
        eng.close()
    del eng

    gc.collect()  # freed buffers must not read as live
    att_closed = attribution()
    log("attribution after close():")
    log(table(att_closed))
    artifact["after_close"] = att_closed
    artifact["released_ok"] = not att_closed["accounted"]


def run_pressure(smoke: bool, artifact: dict) -> None:
    """Constrained budget + mixed workload + seeded HBM_ALLOC storm.
    Gate: zero process deaths / non-shed errors, leases rebalanced
    (T0 shrank, paged constructed), bounded shed rate, post-storm
    token-exact and leak-flat."""
    import numpy as np

    from gofr_tpu import chaos
    from gofr_tpu.errors import TooManyRequests
    from gofr_tpu.testutil.hbmwatch import live_device_bytes
    from gofr_tpu.tpu import GenerationEngine, hbm
    from gofr_tpu.tpu.kvcache import KVCacheOptions

    cfg, params = _tiny_params()
    storm_n = 16 if smoke else 48
    rng = np.random.default_rng(7)

    def mk_prompt(n=24):
        return rng.integers(1, cfg.vocab_size, size=n)

    def contiguous():
        return GenerationEngine(cfg, params, slots=2, max_seq=128,
                                prompt_buckets=(16, 32),
                                prefix_cache_slots=4, prefix_store_min=16,
                                kvcache=KVCacheOptions(host_mb=8))

    def paged():
        return GenerationEngine(cfg, params, slots=2, max_seq=128,
                                prompt_buckets=(16, 32), paged_blocks=12,
                                paged_block_size=16, spec_decode_k=2)

    hbm.reset()
    log(f"hbm_report: pressure mode — storm of {storm_n} mixed requests "
        "over contiguous(prefix T0+T1) + paged(spec) under a tiny budget")
    a = contiguous()
    p_a, p_b = mk_prompt(), mk_prompt(20)
    ref_a = a.generate(p_a, max_new_tokens=6).tokens()
    bytes_a = sum(hbm.live_bytes().values())
    pool_bytes = hbm.live_bytes()["kvcache-t0"]
    b_ref = paged()
    ref_b = b_ref.generate(p_b, max_new_tokens=6).tokens()
    bytes_b = sum(hbm.live_bytes().values()) - bytes_a
    b_ref.close()
    gc.collect()

    # budget that fits A + B only if A's T0 gives up ~half its rows
    row_b = pool_bytes // 4
    budget = bytes_a + bytes_b - 2 * row_b + row_b // 2
    hbm.set_budget(budget)
    slots_before = a._kvc.slots
    a.generate(p_a, max_new_tokens=6).tokens()  # rewarm T0
    b = paged()
    rebalanced = a._kvc.slots < slots_before
    log(f"leases rebalanced: t0 slots {slots_before} -> {a._kvc.slots}, "
        f"budget {budget}")
    log(lease_table(hbm.arbiter_stats()))

    counts = {"ok": 0, "shed": 0, "other": 0}
    sched = chaos.ChaosSchedule(seed=42).on(
        chaos.HBM_ALLOC, error=chaos.ResourceExhausted, p=0.3)
    live_before = live_device_bytes()
    with chaos.scope(sched):
        for i in range(storm_n):
            eng = a if i % 2 == 0 else b
            try:
                eng.generate(mk_prompt(16 + 4 * (i % 3)),
                             max_new_tokens=4).tokens()
                counts["ok"] += 1
            except TooManyRequests:
                counts["shed"] += 1  # the ONLY acceptable failure
            except Exception as e:  # process must never die: record it
                counts["other"] += 1
                log(f"UNEXPECTED error class: {e!r}")
    alive = a.down is None and b.down is None
    # post-storm steady state: token-exact on both engines, leak-flat
    post_a = a.generate(p_a, max_new_tokens=6).tokens()
    post_b = b.generate(p_b, max_new_tokens=6).tokens()
    gc.collect()
    live_after = live_device_bytes()
    shed_rate = counts["shed"] / max(1, storm_n)
    # one seam fire per admission, sequential requests: the shed count
    # must REPRODUCE the seeded schedule exactly — the same
    # determinism contract the chaos smoke pins with its digest diff
    expected_sheds = sum(f for f, _ in
                         sched.decisions(chaos.HBM_ALLOC, storm_n))
    checks = {
        "rebalanced_t0_shrank": rebalanced,
        "zero_process_deaths": alive,
        "zero_non_shed_errors": counts["other"] == 0,
        "some_sheds_observed": counts["shed"] > 0,
        "sheds_match_schedule": counts["shed"] == expected_sheds,
        "bounded_shed_rate": shed_rate <= 0.6,  # p=0.3 + seed variance
        "post_storm_token_exact": post_a == ref_a and post_b == ref_b,
        # tolerance: jit-constant noise, not per-request growth
        "leak_flat": live_after - live_before <= 4 << 20,
    }
    arb = hbm.arbiter_stats()
    log(f"storm counts: {counts}  shed_rate={shed_rate:.2f}")
    log(f"arbiter after storm: reclaims={arb['reclaims']} "
        f"sheds={arb['sheds']}")
    log("checks: " + ", ".join(f"{k}={v}" for k, v in checks.items()))
    slots_after = a._kvc.slots
    a.close()
    b.close()
    hbm.reset()
    artifact.update({
        "bench": "hbm_pressure",
        "smoke": smoke,
        "budget_bytes": budget,
        "t0_slots": {"before": slots_before, "after": slots_after},
        "counts": counts,
        "shed_rate": round(shed_rate, 4),
        "schedule_digest": sched.digest(),
        "arbiter": {"reclaims": arb["reclaims"], "sheds": arb["sheds"],
                    "reclaimed_bytes": arb["reclaimed_bytes"]},
        "checks": checks,
        "ok": all(checks.values()),
    })


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        description="HBM attribution + arbiter report")
    ap.add_argument("--demo", action="store_true",
                    help="force the tiny-engine demo even if the "
                         "registry already has entries")
    ap.add_argument("--pressure", action="store_true",
                    help="memory-pressure acceptance run: constrained "
                         "budget, mixed workload, seeded HBM_ALLOC "
                         "storm; gate = zero deaths + bounded sheds")
    ap.add_argument("--smoke", action="store_true",
                    help="shorter pressure storm (CI)")
    ap.add_argument("--requests", type=int, default=3)
    args = ap.parse_args(argv)

    from gofr_tpu.testutil.hbmwatch import attribution
    from gofr_tpu.tpu import hbm

    artifact: dict = {"tool": "hbm_report"}
    if args.pressure:
        run_pressure(args.smoke, artifact)
        print(json.dumps(artifact))
        return 0 if artifact.get("ok") else 1
    demo = args.demo or not hbm.live_bytes()
    if demo:
        run_demo(args.requests, artifact)
    else:
        att = attribution()
        log("attribution (attach mode):")
        log(table(att))
        log("arbiter lease table:")
        log(lease_table(hbm.arbiter_stats()))
        artifact["serving"] = att
        artifact["arbiter"] = hbm.arbiter_stats()
    print(json.dumps(artifact))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
