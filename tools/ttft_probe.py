"""TTFT decomposition probe: where does first-token latency actually go?

Runs the bench_ttft workload (8B int8 engine, 30 busy decode slots,
probe prompts 128/256/512) and, for every probe, splits the observed
client TTFT into the engine's trace stamps (gofr_tpu/tpu/generator.py
GenStream.trace):

    wait     = admit        - submit        admission wait (decode block
                                            in flight when we arrived)
    prefill  = prefill_done - admit         the prefill dispatch itself
    store    = first_put    - prefill_done  prefix-store row copy etc.
    deliver  = client_recv  - first_put     queue wake-up + GIL

Optionally (--grpc) runs the same probes through a localhost grpcx
server-stream and reports the transport hop's extra cost per segment
(the server handler records when the request reached it).

Usage:  python tools/ttft_probe.py [--grpc] [--slots N] [--block K]
"""

from __future__ import annotations

import argparse
import statistics
import sys
import time

sys.path.insert(0, __import__("os").path.join(
    __import__("os").path.dirname(__import__("os").path.abspath(__file__)),
    ".."))


def med(xs):
    return statistics.median(xs) if xs else float("nan")


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--grpc", action="store_true")
    ap.add_argument("--slots", type=int, default=32)
    ap.add_argument("--block", type=int, default=4,
                    help="engine decode_block (serving default 4)")
    ap.add_argument("--probes", type=int, default=5)
    ap.add_argument("--admit-window-ms", type=float, default=None)
    ap.add_argument("--cpu", action="store_true",
                    help="force host backend (the box sitecustomize pins "
                         "the platform, so JAX_PLATFORMS=cpu is too late)")
    ap.add_argument("--idle-prefill", action="store_true",
                    help="also time raw prefill dispatches per bucket on "
                         "an idle engine (no background decode)")
    args = ap.parse_args()

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp
    import numpy as np

    sys.path.insert(0, ".")
    from bench import int8_random_params
    from gofr_tpu.models.common import LLAMA_CONFIGS
    from gofr_tpu.tpu import GenerationEngine

    platform = jax.devices()[0].platform
    cfg = (LLAMA_CONFIGS["llama3-8b"] if platform != "cpu"
           else LLAMA_CONFIGS["tiny"])
    probe_lens = (128, 256, 512) if platform != "cpu" else (16, 32)
    print(f"platform={platform} slots={args.slots} block={args.block}",
          file=sys.stderr)

    kw = {}
    if args.admit_window_ms is not None:
        kw["admit_window_ms"] = args.admit_window_ms
    params = int8_random_params(cfg, jax.random.PRNGKey(0))
    engine = GenerationEngine(cfg, params, slots=args.slots, max_seq=1024,
                              prompt_buckets=probe_lens,
                              kv_dtype=jnp.int8, decode_block=args.block,
                              **kw)
    rng = np.random.default_rng(0)
    try:
        engine.warmup()
        if args.idle_prefill:
            # raw prefill dispatch on the idle engine: generate() with no
            # background decode — admission is immediate, so trace
            # prefill ≈ the dispatch itself
            print("\nidle prefill (ms, median):", file=sys.stderr)
            for plen in probe_lens:
                ts = []
                for _ in range(args.probes):
                    s = engine.generate(
                        rng.integers(1, cfg.vocab_size, plen).tolist(),
                        max_new_tokens=1)
                    s.tokens()
                    tr = s.trace
                    ts.append((tr["prefill_done"] - tr["admit"]) * 1e3)
                print(f"  {plen:>5} {med(ts):8.1f}", file=sys.stderr)
        background = [
            engine.generate(rng.integers(1, cfg.vocab_size, 64).tolist(),
                            max_new_tokens=4096)
            for _ in range(max(0, args.slots - 2))
        ]
        time.sleep(0.5)

        def probe_engine(plen: int) -> dict:
            prompt = rng.integers(1, cfg.vocab_size, plen).tolist()
            # decorrelate from the block cycle (serial probes otherwise
            # phase-lock their submit to a reap boundary)
            time.sleep(rng.uniform(0.0, 0.15))
            t0 = time.monotonic()
            s = engine.generate(prompt, max_new_tokens=2)
            it = iter(s)
            next(it)
            t1 = time.monotonic()
            tr = dict(s.trace)
            s.cancel()
            for _ in it:
                pass
            return {
                "total": (t1 - t0) * 1e3,
                "enqueue": (tr["submit"] - t0) * 1e3,
                "wait": (tr["admit"] - tr["submit"]) * 1e3,
                "prefill": (tr["prefill_done"] - tr["admit"]) * 1e3,
                "store": (tr["first_put"] - tr["prefill_done"]) * 1e3,
                "deliver": (t1 - tr["first_put"]) * 1e3,
            }

        segs = ("total", "enqueue", "wait", "prefill", "store", "deliver")
        rows: dict[int, list[dict]] = {}
        for plen in probe_lens:
            rows[plen] = [probe_engine(plen) for _ in range(args.probes)]
        print("\nengine-level (ms, median over "
              f"{args.probes} probes):", file=sys.stderr)
        print(f"  {'len':>5} " + " ".join(f"{s:>8}" for s in segs),
              file=sys.stderr)
        for plen, rs in rows.items():
            print(f"  {plen:>5} " + " ".join(
                f"{med([r[s] for r in rs]):8.1f}" for s in segs),
                file=sys.stderr)

        if args.grpc:
            from gofr_tpu.grpcx import GRPCServer, GRPCService, dial

            llm = GRPCService("llm.Generation")
            handler_traces = []

            @llm.server_stream("Generate")
            def generate(ctx, req):
                t_in = time.monotonic()
                s = engine.generate(req["tokens"], max_new_tokens=2)
                try:
                    first = True
                    for tok in s:
                        if first:
                            handler_traces.append(
                                {"handler_in": t_in, **s.trace,
                                 "handler_out": time.monotonic()})
                            first = False
                        yield {"token": tok}
                finally:
                    s.cancel()

            srv = GRPCServer([llm], port=0)
            srv.start()
            channel = dial(f"127.0.0.1:{srv.port}")
            try:
                grows = {}
                for plen in probe_lens:
                    samples = []
                    for _ in range(args.probes):
                        prompt = rng.integers(
                            1, cfg.vocab_size, plen).tolist()
                        time.sleep(rng.uniform(0.0, 0.15))  # see above
                        t0 = time.monotonic()
                        it = channel.server_stream(
                            "/llm.Generation/Generate",
                            {"tokens": prompt, "max_new_tokens": 2})
                        next(iter(it))
                        t1 = time.monotonic()
                        tr = handler_traces[-1]
                        samples.append({
                            "total": (t1 - t0) * 1e3,
                            "to_handler": (tr["handler_in"] - t0) * 1e3,
                            "wait": (tr["admit"] - tr["submit"]) * 1e3,
                            "prefill": (tr["prefill_done"]
                                        - tr["admit"]) * 1e3,
                            "h_wake": (tr["handler_out"]
                                       - tr["first_put"]) * 1e3,
                            "to_client": (t1 - tr["handler_out"]) * 1e3,
                        })
                    grows[plen] = samples
                gsegs = ("total", "to_handler", "wait", "prefill",
                         "h_wake", "to_client")
                print("\ngRPC-level (ms, median):", file=sys.stderr)
                print(f"  {'len':>5} " + " ".join(f"{s:>10}" for s in gsegs),
                      file=sys.stderr)
                for plen, rs in grows.items():
                    print(f"  {plen:>5} " + " ".join(
                        f"{med([r[s] for r in rs]):10.1f}" for s in gsegs),
                        file=sys.stderr)
            finally:
                channel.close()
                srv.stop()

        for b in background:
            b.cancel()
    finally:
        engine.close()


if __name__ == "__main__":
    # serialize with any other chip holder (bench.py / retry loop):
    # concurrent TPU clients through the tunnel wedge it for hours
    import bench

    _chip_lock = bench.acquire_chip_lock(section="probe")
    main()
