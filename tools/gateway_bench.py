#!/usr/bin/env python
"""Prefix-affinity gateway multi-process benchmark.

REAL processes: N replica Apps (tiny llama engine, prefix cache on)
each serve /generate over HTTP in their own process; the parent runs
a gateway App (``TPU_SERVING_ROLE=gateway``) fronting them and drives
mixed multi-turn load through it. CPU-only (JAX_PLATFORMS=cpu) — the
structural gates are the point; the goodput comparison is advisory on
a 1-core container (N replicas time-slice one CPU, same caveat class
pd_bench documents).

Arms and gates:

  exactness   one prompt served direct-to-replica vs through the
              gateway: token-exact (STRICT) — the gateway relays, it
              never resamples.
  steady      S multi-turn sessions (distinct first blocks, growing
              tails) + short probes: affinity hit rate from gateway
              stats >= the gate (STRICT — this is what makes replica
              prefix caches worth their HBM), zero failed requests.
  scaling     the same steady load through a 1-replica gateway, then
              the N-replica gateway: aggregate goodput ratio is
              STRICT (>= 60% of linear) with >= N+1 cores, else
              recorded ADVISORY.
  rolling     every replica drained + restarted in sequence under
              load (stdin-close -> App.stop(grace) -> respawn, same
              port): ZERO client-visible failures and ZERO mid-stream
              error lines (STRICT) — readiness flips route new work
              away while in-flight streams finish on the old process.
  kill        one replica (the session-0 affinity owner) SIGKILLed
              mid-load then respawned: every request still serves
              (STRICT zero hard failures) — the death is discovered
              pre-first-token (transport failover) or mid-stream
              (typed 503 line, retried) depending on what was in
              flight at the kill instant, >= 1 of either observed,
              post-recovery token-exact (STRICT).

Output follows the bench stdout contract (tools/README.md): the LAST
stdout line is the JSON artifact; progress goes to stderr. Full runs
write GATEWAY_BENCH.json.
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("TPU_TIMELINE", "0")

SEED_VOCAB = 500
BLOCK = 16
PREFIX_LEN = 32     # two full affinity blocks per session
TURN_GROWTH = 8
MAX_PROMPT = 64
EXACT_PROMPT_LEN = 40


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


# -- child process: one serving replica ---------------------------------------

def run_replica(port: int) -> None:
    from gofr_tpu import App
    from gofr_tpu.config import MapConfig

    app = App(MapConfig({
        "APP_NAME": f"replica-{port}", "LOG_LEVEL": "ERROR",
        "HTTP_PORT": str(port), "METRICS_PORT": "0",
        "TPU_MODEL": "tiny", "TPU_MAX_SEQ": "256", "TPU_SLOTS": "4",
        "TPU_SEQ_BUCKETS": "32,64,96", "TPU_DECODE_BLOCK": "4",
        "TPU_PREFIX_CACHE": "4", "TPU_PREFIX_MIN": str(PREFIX_LEN),
        "TPU_KVCACHE_BLOCK": str(BLOCK),
        "TPU_WARMUP": "true",
    }))
    if app.container.tpu is None:
        print("ENGINE-FAILED", flush=True)
        return

    @app.post("/generate")
    def generate(ctx):
        body = ctx.bind()
        stream = ctx.tpu.generate(
            body["tokens"], max_new_tokens=body.get("max_new_tokens", 8),
            temperature=0.0)
        ctx.stream(stream.map(
            lambda t: (json.dumps({"token": int(t)}) + "\n").encode()))
        return None

    app.run(block=False)
    print(f"READY {app.http_port}", flush=True)
    try:
        sys.stdin.read()  # parent closes stdin -> graceful drain
    except Exception:
        pass
    app.stop(grace_s=10.0)


class ReplicaProc:
    """Spawn/respawn handle for one replica child pinned to one port
    (the gateway's replica list is static config)."""

    def __init__(self, port: int):
        self.port = port
        self.proc: subprocess.Popen | None = None

    @property
    def address(self) -> str:
        return f"127.0.0.1:{self.port}"

    def spawn(self) -> None:
        env = dict(os.environ, JAX_PLATFORMS="cpu", TPU_TIMELINE="0")
        self.proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--worker",
             "replica", "--port", str(self.port)],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE, env=env,
            text=True)

    def wait_ready(self, timeout_s: float = 180.0) -> None:
        assert self.proc is not None
        line = self.proc.stdout.readline().strip()
        if not line.startswith("READY "):
            raise RuntimeError(f"replica :{self.port} failed: {line!r}")
        # DRAIN the child's stdout forever: the framework emits one
        # wide event PER REQUEST on stdout unconditionally (it bypasses
        # the log-level gate by design), so an undrained pipe fills at
        # ~64 KiB and the replica's serving loop then blocks on its
        # own telemetry write — a wedge that looks exactly like an
        # engine deadlock (found the hard way; stacks end in glog._logf)
        out = self.proc.stdout
        threading.Thread(target=lambda: [None for _ in out],
                         name=f"drain-{self.port}", daemon=True).start()

    def drain_stop(self) -> None:
        """Graceful: stdin-close triggers App.stop(grace) in the child
        — readiness flips first, in-flight streams finish."""
        if self.proc is not None:
            try:
                self.proc.stdin.close()
                self.proc.wait(timeout=60)
            except Exception:
                self.proc.kill()
            self.proc = None

    def kill(self) -> None:
        if self.proc is not None:
            self.proc.kill()
            self.proc.wait()
            self.proc = None


def free_ports(n: int) -> list[int]:
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


# -- the client side ----------------------------------------------------------

class Counts:
    def __init__(self):
        self.ok = 0
        self.sheds = 0            # typed 429/503 responses, retried
        self.midstream = 0        # terminal typed error lines, retried
        self.hard = 0             # anything else: the zero-loss gate
        self.hard_reprs: list[str] = []
        self.tokens = 0
        self.lock = threading.Lock()


def post_generate(port: int, tokens, max_new: int, timeout: float = 60.0):
    """-> (status, headers, lines). Raises OSError family on transport
    failure (the gateway itself should never drop the connection)."""
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/generate",
        data=json.dumps({"tokens": [int(t) for t in tokens],
                         "max_new_tokens": int(max_new)}).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            lines = [json.loads(line) for line in
                     resp.read().decode().splitlines() if line]
            return resp.status, dict(resp.headers), lines
    except urllib.error.HTTPError as e:
        body = e.read()
        try:
            detail = json.loads(body)
        except Exception:
            detail = {}
        return e.code, dict(e.headers), detail


def run_turn(gw_port: int, prompt, max_new: int, counts: Counts,
             stop: threading.Event, deadline_s: float = 60.0) -> list[int]:
    """One session turn through the gateway, retrying typed sheds and
    typed mid-stream losses until served (or the turn deadline —
    counted HARD: zero-loss means every request eventually serves)."""
    t_end = time.monotonic() + deadline_s
    while not stop.is_set():
        try:
            status, headers, lines = post_generate(gw_port, prompt, max_new)
        except Exception as e:  # noqa: BLE001 — gateway conn loss = hard
            with counts.lock:
                counts.hard += 1
                if len(counts.hard_reprs) < 8:
                    counts.hard_reprs.append(repr(e))
            return []
        if status == 200:
            toks = [ln["token"] for ln in lines if "token" in ln]
            errs = [ln for ln in lines if "error" in ln]
            if errs:
                with counts.lock:
                    counts.midstream += 1
                if time.monotonic() < t_end:
                    stop.wait(min(errs[-1]["error"].get("retry_after",
                                                        0.3), 1.0))
                    continue
            else:
                with counts.lock:
                    counts.ok += 1
                    counts.tokens += len(toks)
                return toks
        elif status in (429, 503):
            with counts.lock:
                counts.sheds += 1
            if time.monotonic() < t_end:
                try:
                    ra = float(headers.get("Retry-After", 0.3))
                except ValueError:
                    ra = 0.3
                stop.wait(min(ra, 1.0))
                continue
        with counts.lock:
            counts.hard += 1
            if len(counts.hard_reprs) < 8:
                counts.hard_reprs.append(f"status={status}")
        return []
    return []


class Load:
    """S closed-loop multi-turn sessions + one short-probe loop."""

    def __init__(self, gw_port: int, sessions: int, max_new: int,
                 counts: Counts):
        self.stop = threading.Event()
        self.threads = []
        for s in range(sessions):
            prefix = [(s * 131 + j) % SEED_VOCAB + 1
                      for j in range(PREFIX_LEN)]
            self.threads.append(threading.Thread(
                target=self._session, args=(gw_port, prefix, max_new,
                                            counts), daemon=True))
        self.threads.append(threading.Thread(
            target=self._probes, args=(gw_port, counts), daemon=True))

    def _session(self, gw_port, prefix, max_new, counts):
        turn = 0
        while not self.stop.is_set():
            tail = [(turn * 17 + j) % SEED_VOCAB + 1
                    for j in range(min(turn, 4) * TURN_GROWTH)]
            prompt = (prefix + tail)[:MAX_PROMPT]
            run_turn(gw_port, prompt, max_new, counts, self.stop)
            turn += 1

    def _probes(self, gw_port, counts):
        i = 0
        while not self.stop.is_set():
            prompt = [(i * 7 + j) % SEED_VOCAB + 1 for j in range(8)]
            run_turn(gw_port, prompt, 2, counts, self.stop)
            i += 1
            self.stop.wait(0.2)

    def start(self):
        for t in self.threads:
            t.start()

    def finish(self):
        self.stop.set()
        for t in self.threads:
            t.join(timeout=90)


def gw_stats(port: int) -> dict:
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/gateway/stats", timeout=10) as r:
        return json.loads(r.read())["data"]


def build_gateway(replica_addrs: list[str]):
    from gofr_tpu import App
    from gofr_tpu.config import MapConfig

    gw = App(MapConfig({
        "APP_NAME": "gateway-bench", "LOG_LEVEL": "ERROR",
        "HTTP_PORT": "0", "METRICS_PORT": "0",
        "TPU_SERVING_ROLE": "gateway",
        "TPU_GATEWAY_REPLICAS": ",".join(replica_addrs),
        "TPU_GATEWAY_BLOCK": str(BLOCK),
        "TPU_GATEWAY_HEALTH_INTERVAL_S": "0.5",
        "TPU_GATEWAY_CONNECT_TIMEOUT_S": "2.0",
    }))
    gw.run(block=False)
    return gw


def measure_window(gw_port: int, sessions: int, max_new: int,
                   window_s: float) -> tuple[Counts, float]:
    counts = Counts()
    load = Load(gw_port, sessions, max_new, counts)
    t0 = time.monotonic()
    load.start()
    time.sleep(window_s)
    load.finish()
    return counts, time.monotonic() - t0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--worker", choices=["replica"])
    ap.add_argument("--port", type=int, default=0)
    args = ap.parse_args()
    if args.worker == "replica":
        run_replica(args.port)
        return 0

    smoke = args.smoke
    n_replicas = 2 if smoke else 3
    sessions = 3 if smoke else 4
    window_s = 6.0 if smoke else 12.0
    max_new = 4 if smoke else 6
    cores = os.cpu_count() or 1
    scaling_gated = cores >= n_replicas + 1

    payload: dict = {"bench": "gateway", "smoke": smoke,
                     "replicas": n_replicas, "sessions": sessions,
                     "cores": cores, "scaling_gated": scaling_gated}

    ports = free_ports(n_replicas)
    reps = [ReplicaProc(p) for p in ports]
    log(f"spawning {n_replicas} replicas on {ports}...")
    for r in reps:
        r.spawn()
    for r in reps:
        r.wait_ready()
    log("replicas ready")

    exact_prompt = [(j * 13) % SEED_VOCAB + 1
                    for j in range(EXACT_PROMPT_LEN)]

    # -- scaling baseline: the same load through a 1-replica gateway --
    gw1 = build_gateway([reps[0].address])
    log("scaling baseline: 1-replica gateway under steady load...")
    c1, dur1 = measure_window(gw1.http_port, sessions, max_new, window_s)
    goodput_1 = c1.tokens / dur1
    gw1.stop()
    log(f"1-replica goodput: {goodput_1:.1f} tok/s "
        f"(ok={c1.ok} hard={c1.hard})")

    gw = build_gateway([r.address for r in reps])
    gw_port = gw.http_port

    try:
        # -- exactness: gateway relays, never resamples ----------------
        _, _, direct = post_generate(reps[0].port, exact_prompt, 12)
        status, _, via_gw = post_generate(gw_port, exact_prompt, 12)
        exact_ok = (status == 200 and
                    [x["token"] for x in via_gw if "token" in x]
                    == [x["token"] for x in direct if "token" in x])
        payload["exact_tokens"] = exact_ok
        log(f"exactness gateway-vs-direct: {exact_ok}")

        # -- steady: affinity + zero failures --------------------------
        s_before = gw_stats(gw_port)
        log(f"steady arm: {sessions} multi-turn sessions, "
            f"{window_s:.0f}s...")
        cs, dur = measure_window(gw_port, sessions, max_new, window_s)
        s_after = gw_stats(gw_port)
        picks_d = {k: s_after["router"]["picks"][k]
                   - s_before["router"]["picks"][k]
                   for k in ("hit", "spill", "short")}
        affinity = picks_d["hit"] / max(1, picks_d["hit"]
                                        + picks_d["spill"])
        goodput_n = cs.tokens / dur
        payload["steady"] = {
            "ok": cs.ok, "sheds": cs.sheds, "midstream": cs.midstream,
            "hard_failures": cs.hard, "hard_reprs": cs.hard_reprs,
            "picks": picks_d, "affinity_hit_rate": round(affinity, 4),
            "goodput_tok_s": round(goodput_n, 2)}
        payload["scaling"] = {
            "goodput_1_tok_s": round(goodput_1, 2),
            "goodput_n_tok_s": round(goodput_n, 2),
            "ratio": round(goodput_n / max(goodput_1, 1e-9), 3),
            "linear": float(n_replicas),
            "note": ("strict" if scaling_gated else
                     "advisory: replicas time-slice "
                     f"{cores} core(s) — near-linear scaling needs "
                     "one core per process")}
        log(f"steady: affinity={affinity:.2f} goodput={goodput_n:.1f} "
            f"tok/s (x{payload['scaling']['ratio']} vs 1 replica) "
            f"hard={cs.hard}")

        # -- rolling restart: zero loss --------------------------------
        log("rolling restart arm: drain+respawn every replica "
            "under load...")
        cr = Counts()
        load = Load(gw_port, sessions, max_new, cr)
        load.start()
        time.sleep(1.0)
        for i, r in enumerate(reps):
            log(f"  draining replica {i} (:{r.port})...")
            r.drain_stop()
            r.spawn()
            r.wait_ready()
            log(f"  replica {i} restarted")
            time.sleep(0.5)  # let the poller re-admit it
        time.sleep(1.0)
        load.finish()
        payload["rolling"] = {
            "ok": cr.ok, "sheds": cr.sheds, "midstream": cr.midstream,
            "hard_failures": cr.hard, "hard_reprs": cr.hard_reprs,
            "drain_failovers":
                gw_stats(gw_port)["failovers"]["drain"]}
        log(f"rolling: {payload['rolling']}")

        # -- SIGKILL + failover recovery -------------------------------
        # kill the replica that OWNS session 0's affinity arc (the
        # same ring + first-block hash the gateway routes by), so the
        # dead socket is guaranteed live traffic before the health
        # poller can discover the death — the pre-first-token
        # failover path, not the poller, must absorb the kill
        from gofr_tpu.gateway import HashRing
        from gofr_tpu.tpu.kvcache import first_block_hash

        ring = HashRing([r.address for r in reps])
        sess0 = [(0 * 131 + j) % SEED_VOCAB + 1 for j in range(PREFIX_LEN)]
        victim = ring.order(first_block_hash(sess0, BLOCK))[0]
        log(f"kill arm: SIGKILL replica {victim} (session-0 affinity "
            "owner) mid-load...")
        f_before = gw_stats(gw_port)["failovers"]["transport"]
        ck = Counts()
        load = Load(gw_port, sessions, max_new, ck)
        load.start()
        time.sleep(1.0)
        reps[victim].kill()
        log(f"  replica {victim} KILLED")
        time.sleep(max(3.0, window_s / 3))
        reps[victim].spawn()
        reps[victim].wait_ready()
        log(f"  replica {victim} respawned")
        time.sleep(1.5)
        load.finish()
        f_after = gw_stats(gw_port)["failovers"]["transport"]
        _, _, post = post_generate(gw_port, exact_prompt, 12)
        post_exact = ([x["token"] for x in post if "token" in x]
                      == [x["token"] for x in direct if "token" in x])
        payload["kill"] = {
            "ok": ck.ok, "sheds": ck.sheds, "midstream": ck.midstream,
            "hard_failures": ck.hard, "hard_reprs": ck.hard_reprs,
            "transport_failovers": f_after - f_before,
            "post_recovery_exact": post_exact}
        log(f"kill: {payload['kill']}")

        payload["gateway_stats"] = gw_stats(gw_port)
    finally:
        gw.stop()
        for r in reps:
            r.drain_stop()

    affinity_gate = 0.75
    checks = {
        "exact_tokens": bool(payload["exact_tokens"]),
        "steady_zero_failures":
            payload["steady"]["hard_failures"] == 0
            and payload["steady"]["midstream"] == 0,
        "affinity_hit_rate_ok":
            payload["steady"]["affinity_hit_rate"] >= affinity_gate,
        "rolling_zero_loss":
            payload["rolling"]["hard_failures"] == 0
            and payload["rolling"]["midstream"] == 0,
        # the kill is discovered EITHER pre-first-token (a transport
        # failover: the next connect hits the dead socket) or
        # mid-stream (the in-flight relay dies -> typed 503 line,
        # retried, and the loss marks the replica down so no further
        # connect is ever attempted) — which one depends on what was
        # in flight at the instant of death, so the gate accepts
        # either. The deterministic pre-first-token path is pinned by
        # tests/test_gateway.py (poller frozen, token-exact).
        "kill_arm_recovered":
            payload["kill"]["hard_failures"] == 0
            and (payload["kill"]["transport_failovers"] >= 1
                 or payload["kill"]["midstream"] >= 1)
            and payload["kill"]["post_recovery_exact"],
        "scaling_near_linear":
            payload["scaling"]["ratio"] >= 0.6 * n_replicas,
    }
    strict = [k for k in checks if k != "scaling_near_linear"]
    if scaling_gated:
        strict.append("scaling_near_linear")
    payload["checks"] = checks
    payload["affinity_gate"] = affinity_gate
    payload["ok"] = all(checks[k] for k in strict)
    print(json.dumps(payload), flush=True)
    return 0 if payload["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
