#!/usr/bin/env python
"""Dump (or self-host and validate) the serving timeline as
Chrome-trace/Perfetto JSON.

Two modes:

  --url http://host:2121 [--last-ms N] [--out trace.json]
      Fetch ``/debug/timeline`` from a running app's metrics port and
      write the Chrome-trace JSON (stdout or --out). Load the file in
      ui.perfetto.dev or chrome://tracing.

  --smoke / (no args: full run)
      CPU-only, no chip lock: host a tiny engine in-process, record a
      mixed serving window (latency probes + throughput-class chunked
      prefills + concurrent decode), export the timeline, and validate
      the trace against the run's KNOWN schedule:

        - the trace is valid Chrome-trace JSON with per-slot decode
          tracks, prefill-chunk slices (index+length), and at least
          one HBM counter track;
        - chunk indices are consecutive per admission and every
          track's slices are timestamp-ordered;
        - admit instants cover every served request.

      It also measures the emission cost the tentpole promises to keep
      off the books: the per-event append latency (on vs off) and the
      decode hot path's block cadence with the timeline enabled vs
      disabled (TPU_TIMELINE=0 equivalent). Full runs write
      TIMELINE_BENCH.json.

Output follows the bench stdout contract (tools/README.md): the LAST
stdout line is the JSON artifact; progress goes to stderr; failures
land in a ``failures`` list instead of a non-zero exit.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


# -- fetch mode ---------------------------------------------------------------

def fetch(url: str, last_ms: float | None, out: str | None) -> int:
    import urllib.request

    target = url.rstrip("/") + "/debug/timeline"
    if last_ms is not None:
        target += f"?last_ms={last_ms}"
    log(f"fetching {target}")
    with urllib.request.urlopen(target, timeout=10) as r:
        payload = r.read()
    json.loads(payload)  # refuse to write a non-JSON body
    if out:
        Path(out).write_bytes(payload)
        log(f"wrote {out} ({len(payload)} bytes) — load in ui.perfetto.dev")
    else:
        sys.stdout.write(payload.decode())
    return 0


# -- smoke / bench mode -------------------------------------------------------

def _build_engine(timeline_enabled: bool, metrics=None):
    import jax

    from gofr_tpu.models import LLAMA_CONFIGS, llama
    from gofr_tpu.observe import Observe, Timeline
    from gofr_tpu.tpu import GenerationEngine

    cfg = LLAMA_CONFIGS["tiny"]
    params = llama.init(cfg, jax.random.PRNGKey(0))
    obs = Observe(metrics=metrics,
                  timeline=Timeline(capacity=65536,
                                    enabled=timeline_enabled))
    eng = GenerationEngine(cfg, params, slots=2, max_seq=256,
                           prompt_buckets=(8, 16, 32), prefill_chunk=16,
                           decode_block=4, metrics=metrics, observe=obs)
    return eng, obs


def _mixed_window(eng, n_probes: int):
    """The recorded window: one long throughput-class chunked prefill
    per probe round, interleaved with short latency-class probes and a
    background decode stream."""
    import numpy as np

    from gofr_tpu.resilience import SLO_LATENCY, SLO_THROUGHPUT

    rng = np.random.default_rng(7)
    V = eng.cfg.vocab_size
    background = eng.generate(rng.integers(1, V, 4).tolist(),
                              max_new_tokens=8 * n_probes,
                              slo_class=SLO_LATENCY)
    served = []
    for _ in range(n_probes):
        long_stream = eng.generate(rng.integers(1, V, 60).tolist(),
                                   max_new_tokens=4,
                                   slo_class=SLO_THROUGHPUT)
        served.append(("long", long_stream, long_stream.tokens()))
        probe = eng.generate(rng.integers(1, V, 4).tolist(),
                             max_new_tokens=4, slo_class=SLO_LATENCY)
        served.append(("probe", probe, probe.tokens()))
    background.cancel()
    list(background)
    return served


def _validate_trace(trace: dict, served) -> list[str]:
    failures: list[str] = []
    ev = trace.get("traceEvents", [])
    cats = {}
    for e in ev:
        cats.setdefault(e.get("cat", e.get("ph")), []).append(e)

    if not cats.get("decode"):
        failures.append("no per-slot decode slices")
    else:
        tids = {e["tid"] for e in cats["decode"]}
        if not tids <= {10, 11}:
            failures.append(f"decode slices off the slot tracks: {tids}")
    if not cats.get("chunk"):
        failures.append("no prefill-chunk slices")
    else:
        # chunk indices are consecutive runs per admission
        per_req: dict = {}
        for e in cats["chunk"]:
            per_req.setdefault(e["args"]["request_id"], []).append(
                e["args"]["chunk_index"])
        for rid, idxs in per_req.items():
            if idxs != list(range(len(idxs))):
                failures.append(
                    f"chunk indices for request {rid} not consecutive: "
                    f"{idxs}")
    if not any(e.get("ph") == "C" and str(e.get("name", "")).startswith(
            "hbm:") for e in ev):
        failures.append("no HBM counter track")
    admits = cats.get("sched", []) or []
    n_admits = sum(1 for e in admits if e.get("name") == "admit")
    n_served = sum(1 for kind, s, toks in served if toks)
    if n_admits < n_served:
        failures.append(f"{n_admits} admit instants < {n_served} served")
    # per-track timestamp ordering
    by_tid: dict = {}
    for e in ev:
        if e.get("ph") == "X":
            by_tid.setdefault(e["tid"], []).append(e["ts"])
    for tid, ts in by_tid.items():
        if ts != sorted(ts):
            failures.append(f"track {tid} slices out of order")
    names = {e["args"]["name"] for e in ev
             if e.get("ph") == "M" and e["name"] == "thread_name"}
    if "slot 0" not in names:
        failures.append(f"missing slot-track metadata: {names}")
    return failures


def _append_cost_us(enabled: bool, n: int = 200_000) -> float:
    from gofr_tpu.observe import Timeline

    tl = Timeline(capacity=65536, enabled=enabled)
    t0 = time.perf_counter()
    for _ in range(n):
        tl.append("decode", 0.0, 0.001, (0, 1), 4)
    return (time.perf_counter() - t0) / n * 1e6


def _decode_cadence_ms(eng, tokens: int = 96) -> list[float]:
    """Block-cadence samples for one greedy stream: the gap between
    successive fused-block deliveries (the decode hot path the
    timeline's overhead would tax)."""
    import numpy as np

    rng = np.random.default_rng(3)
    stream = eng.generate(rng.integers(1, eng.cfg.vocab_size, 8).tolist(),
                          max_new_tokens=tokens)
    gaps, last = [], None
    block = eng.decode_block
    for i, _tok in enumerate(stream):
        if i % block == 0:
            now = time.perf_counter()
            if last is not None:
                gaps.append((now - last) * 1e3)
            last = now
    return gaps


def run_bench(smoke: bool) -> dict:
    from gofr_tpu.metrics import Manager, register_framework_metrics

    art: dict = {"bench": "timeline", "smoke": smoke}
    failures: list[str] = []

    metrics = Manager()
    register_framework_metrics(metrics)
    log("timeline_dump: building engine (timeline ON)")
    eng_on, obs = _build_engine(True, metrics=metrics)
    try:
        served = _mixed_window(eng_on, n_probes=2 if smoke else 6)
        bad = [k for k, s, toks in served if not toks]
        if bad:
            failures.append(f"streams yielded no tokens: {bad}")
        trace = obs.timeline.chrome_trace()
        art["events_recorded"] = obs.timeline.stats()["total_recorded"]
        art["trace_events"] = len(trace.get("traceEvents", []))
        failures += _validate_trace(trace, served)
        cadence_on = _decode_cadence_ms(eng_on, 64 if smoke else 256)
    finally:
        eng_on.close()

    log("timeline_dump: building engine (timeline OFF) for the A/B")
    eng_off, _ = _build_engine(False, metrics=metrics)
    try:
        cadence_off = _decode_cadence_ms(eng_off, 64 if smoke else 256)
    finally:
        eng_off.close()

    on_us = _append_cost_us(True, 50_000 if smoke else 200_000)
    off_us = _append_cost_us(False, 50_000 if smoke else 200_000)
    art["append_ns_per_event"] = {"enabled": round(on_us * 1e3, 1),
                                  "disabled": round(off_us * 1e3, 1)}
    if on_us > 25.0:
        failures.append(f"append cost {on_us:.2f}us > 25us budget")
    if off_us > 5.0:
        failures.append(f"disabled append cost {off_us:.2f}us > 5us")

    p50_on = statistics.median(cadence_on) if cadence_on else None
    p50_off = statistics.median(cadence_off) if cadence_off else None
    art["decode_block_cadence_ms"] = {
        "timeline_on_p50": round(p50_on, 4) if p50_on else None,
        "timeline_off_p50": round(p50_off, 4) if p50_off else None,
        # informational: on CPU the block time (ms) dwarfs one append
        # (sub-µs), so this ratio measures noise more than overhead —
        # the append micro-bench above is the gated number
        "on_over_off": (round(p50_on / p50_off, 3)
                        if p50_on and p50_off else None),
    }
    art["failures"] = failures
    art["ok"] = not failures
    return art


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--url", help="metrics-port base URL of a running app")
    ap.add_argument("--last-ms", type=float, default=None)
    ap.add_argument("--out", help="write the trace/artifact to this file")
    ap.add_argument("--smoke", action="store_true",
                    help="fast CI arm of the self-hosted bench")
    args = ap.parse_args()

    if args.url:
        return fetch(args.url, args.last_ms, args.out)

    art = run_bench(smoke=args.smoke)
    if not args.smoke:
        out = args.out or str(Path(__file__).resolve().parent.parent
                              / "TIMELINE_BENCH.json")
        Path(out).write_text(json.dumps(art, indent=2) + "\n")
        log(f"wrote {out}")
    print(json.dumps(art))
    return 0


if __name__ == "__main__":
    sys.exit(main())
