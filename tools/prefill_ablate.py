"""Ablate the serving prefill dispatch to find where its time goes.

The TTFT decomposition (tools/ttft_probe.py) showed the prefill dispatch
dominating first-token latency on hardware (~160 ms for a 128-token
prompt where the weight-stream roofline says ~15 ms). This times the
same [1, Sb] serving prefill under surgical variants, one jit each:

    full        logits + KV stacks + quantize-on-write into the cache
                (exactly GenerationEngine._prefill_fn)
    nологits    skip lm_head entirely
    logit_pos   lm_head at ONE gathered position (the serving fix)
    no_write    return KV stacks, never touch the cache
    no_flash    jnp reference attention instead of the Pallas kernel
    fwd_only    _causal_scan without collecting KV stacks at all

Run it on the TPU backend when the tunnel is up:

    python tools/prefill_ablate.py [--lens 128,256,512] [--iters 20]

Prints one line per (len, variant) with median ms.
"""

from __future__ import annotations

import argparse
import functools
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))
sys.path.insert(0, ".")


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--lens", default="128,256,512")
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--slots", type=int, default=32)
    ap.add_argument("--max-seq", type=int, default=1024)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp
    import numpy as np

    from bench import int8_random_params
    from gofr_tpu.models import llama
    from gofr_tpu.models.common import LLAMA_CONFIGS

    platform = jax.devices()[0].platform
    cfg = (LLAMA_CONFIGS["llama3-8b"] if platform != "cpu"
           else LLAMA_CONFIGS["tiny"])
    lens = tuple(int(x) for x in args.lens.split(","))
    if platform == "cpu":
        lens = tuple(min(x, 32) for x in lens)
    print(f"platform={platform} cfg={cfg.dim}d x {cfg.n_layers}L "
          f"slots={args.slots}", file=sys.stderr)

    params = int8_random_params(cfg, jax.random.PRNGKey(0))
    cache = llama.init_cache(cfg, args.slots, args.max_seq, dtype=jnp.int8)
    rope = llama.get_rope_tables(cfg, args.max_seq)

    def full(cache, params, tokens, length, slot, flash, write,
             logits_mode):
        if logits_mode == "none":  # skip lm_head entirely
            x, (k, v), _, _ = llama._causal_scan(
                params, cfg, tokens, jnp.asarray([length]), args.max_seq,
                rope, None, collect_kv=True, flash=flash)
            out = x[0, 0, 0]  # keep a data dependency on the forward
        else:
            kw = {}
            if logits_mode == "pos":
                kw["logit_pos"] = jnp.asarray([length - 1])
            logits, k, v, _ = llama.prefill_kv(
                params, cfg, tokens, jnp.asarray([length]),
                rope_max=args.max_seq, rope_tables=rope, flash=flash, **kw)
            out = logits[0, 0] if logits_mode == "pos" else \
                jnp.take(logits[0], length - 1, axis=0)
        if write:
            lengths = cache.lengths.at[slot].set(length)
            cache = llama.write_kv(cache, k, v, (0, slot, 0, 0, 0), lengths)
        return out, cache

    def fwd_only(cache, params, tokens, length):
        x = llama.forward(params, cfg, tokens, jnp.asarray([length]),
                          rope_tables=rope)
        return x[0, 0, 0], cache

    variants = {
        "full": dict(flash=platform != "cpu", write=True,
                     logits_mode="full"),
        "logit_pos": dict(flash=platform != "cpu", write=True,
                          logits_mode="pos"),
        "no_logits": dict(flash=platform != "cpu", write=True,
                          logits_mode="none"),
        "no_write": dict(flash=platform != "cpu", write=False,
                         logits_mode="pos"),
        "no_flash": dict(flash=False, write=True, logits_mode="pos"),
    }

    rng = np.random.default_rng(0)
    for plen in lens:
        for name, kv in variants.items():
            jitted = jax.jit(
                functools.partial(full, **kv),
                donate_argnums=(0,), static_argnums=(4,))
            tokens = jnp.asarray(
                rng.integers(1, cfg.vocab_size, (1, plen)), jnp.int32)
            try:
                out, cache = jitted(cache, params, tokens, plen, 0)
                np.asarray(out)
                ts = []
                for _ in range(args.iters):
                    t0 = time.perf_counter()
                    out, cache = jitted(cache, params, tokens, plen, 0)
                    np.asarray(out)
                    ts.append((time.perf_counter() - t0) * 1e3)
                print(f"  len={plen:4d} {name:10s} "
                      f"{statistics.median(ts):8.2f} ms")
            except Exception as e:
                print(f"  len={plen:4d} {name:10s} FAILED "
                      f"{type(e).__name__}: {str(e)[:120]}")
        # forward-only baseline (no KV collection at all)
        jitted = jax.jit(fwd_only, donate_argnums=(0,))
        tokens = jnp.asarray(
            rng.integers(1, cfg.vocab_size, (1, plen)), jnp.int32)
        out, cache = jitted(cache, params, tokens, plen)
        np.asarray(out)
        ts = []
        for _ in range(args.iters):
            t0 = time.perf_counter()
            out, cache = jitted(cache, params, tokens, plen)
            np.asarray(out)
            ts.append((time.perf_counter() - t0) * 1e3)
        print(f"  len={plen:4d} {'fwd_only':10s} "
              f"{statistics.median(ts):8.2f} ms")


if __name__ == "__main__":
    # serialize with any other chip holder (bench.py / retry loop):
    # concurrent TPU clients through the tunnel wedge it for hours
    import bench

    _chip_lock = bench.acquire_chip_lock(section="probe")
    main()
