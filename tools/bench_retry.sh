#!/bin/bash
# Retry `python bench.py` until a COMPLETE clean line lands (headline +
# ttft + engine + prefix + spec + paged sections all measured), saving
# the best line seen so far to BENCH_CANDIDATE.json along the way.
# Rationale: the axon tunnel outages (r03/r04) are multi-hour but
# intermittent — measuring once at round end loses the round; retrying
# across the whole round captures numbers whenever a grant appears
# (VERDICT r3 "Next round" #1). A partially-errored run (e.g. the
# tunnel died mid-sections) still overwrites an older, thinner
# candidate, but the loop keeps going for the full set.
#
# Usage: nohup tools/bench_retry.sh > /tmp/bench_retry.log 2>&1 &
cd "$(dirname "$0")/.."
ATTEMPT=0
while true; do
  ATTEMPT=$((ATTEMPT + 1))
  echo "=== attempt $ATTEMPT at $(date -u +%FT%TZ) ===" >&2
  OUT=$(GOFR_BENCH_INIT_BUDGET_S=480 timeout 7200 python bench.py 2>/tmp/bench_attempt.stderr)
  LINE=$(echo "$OUT" | tail -1)
  echo "$LINE" >&2
  STATUS=$(echo "$LINE" | python - <<'EOF'
import json, sys
try:
    d = json.loads(sys.stdin.readline())
except Exception:
    print("junk"); raise SystemExit
if "error" in d or d.get("value", 0) <= 0 or "partial" in d:
    print("bad"); raise SystemExit
want = ("ttft_p50_ms", "ttft_grpc_p50_ms", "engine_tok_s",
        "prefix_hit_ttft_ms", "spec_tok_s", "paged_tok_s")
print("complete" if all(k in d for k in want) else "usable")
EOF
)
  if [ "$STATUS" = "complete" ] || [ "$STATUS" = "usable" ]; then
    python - "$LINE" <<'EOF'
import json, sys, time
d = json.loads(sys.argv[1])
d["captured_at"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
# keep the richer artifact: never clobber a complete candidate with a
# thinner one unless the old one has gone stale (>24h)
try:
    old = json.load(open("BENCH_CANDIDATE.json"))
    cap = time.strptime(old.get("captured_at", "1970-01-01T00:00:00Z"),
                        "%Y-%m-%dT%H:%M:%SZ")
    import calendar
    fresh = time.time() - calendar.timegm(cap) < 24 * 3600
    if fresh and len([k for k in old if k.endswith("_ms") or
                      k.endswith("_tok_s") or k == "value"]) > \
            len([k for k in d if k.endswith("_ms") or
                 k.endswith("_tok_s") or k == "value"]):
        print("kept richer existing candidate")
        raise SystemExit
except FileNotFoundError:
    pass
json.dump(d, open("BENCH_CANDIDATE.json", "w"), indent=2)
print("saved BENCH_CANDIDATE.json")
EOF
    if [ "$STATUS" = "complete" ]; then
      echo "=== COMPLETE at $(date -u +%FT%TZ) after $ATTEMPT attempts ===" >&2
      exit 0
    fi
    echo "usable but incomplete - retrying for the full set" >&2
  else
    tail -5 /tmp/bench_attempt.stderr >&2
  fi
  sleep 180
done
