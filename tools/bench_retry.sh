#!/bin/bash
# Retry `python bench.py` until one clean (error-free, value>0) line lands,
# then save it to BENCH_CANDIDATE.json with a timestamp. Rationale: the
# axon tunnel outages (r03) are multi-hour but intermittent — measuring
# once at round end loses the round; retrying across the whole round
# captures numbers whenever a grant appears (VERDICT r3 "Next round" #1).
#
# Usage: nohup tools/bench_retry.sh > /tmp/bench_retry.log 2>&1 &
cd "$(dirname "$0")/.."
ATTEMPT=0
while true; do
  ATTEMPT=$((ATTEMPT + 1))
  echo "=== attempt $ATTEMPT at $(date -u +%FT%TZ) ===" >&2
  OUT=$(GOFR_BENCH_INIT_BUDGET_S=480 timeout 3600 python bench.py 2>/tmp/bench_attempt.stderr)
  LINE=$(echo "$OUT" | tail -1)
  echo "$LINE" >&2
  if echo "$LINE" | python -c '
import json, sys
d = json.loads(sys.stdin.readline())
ok = "error" not in d and d.get("value", 0) > 0 and "partial" not in d
sys.exit(0 if ok else 1)
' 2>/dev/null; then
    python - "$LINE" <<'EOF'
import json, sys, time
d = json.loads(sys.argv[1])
d["captured_at"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
with open("BENCH_CANDIDATE.json", "w") as f:
    json.dump(d, f, indent=2)
print("saved BENCH_CANDIDATE.json")
EOF
    echo "=== SUCCESS at $(date -u +%FT%TZ) after $ATTEMPT attempts ===" >&2
    exit 0
  fi
  tail -5 /tmp/bench_attempt.stderr >&2
  sleep 180
done
